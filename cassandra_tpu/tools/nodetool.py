"""nodetool: operator commands over a node/engine.

Reference counterpart: tools/nodetool/ (161 JMX subcommands over
NodeProbe). This framework exposes the same operations as direct Python
API on the Node/StorageEngine (the JMX transport is replaced by in-process
calls; a remote admin protocol can wrap these functions); `python -m
cassandra_tpu.tools.nodetool <cmd> --data <dir>` drives a local engine.

Implemented commands: status, info, flush, compact, compactionstats,
commitlogstats, tablestats, repair, cleanup, gettraces, exportmetrics,
ring, and the breadth registry below (~120 commands).
"""
from __future__ import annotations

import argparse
import json
import sys


def status(node) -> list[dict]:
    """nodetool status: per-endpoint liveness + ownership."""
    out = []
    for ep, toks in node.ring.endpoints.items():
        out.append({"endpoint": ep.name, "dc": ep.dc, "rack": ep.rack,
                    "status": "UN" if node.is_alive(ep) else "DN",
                    "tokens": len(toks)})
    return out


def _cache_line(stats: dict, entries=None, size=None) -> dict:
    hits = int(stats.get("hits", 0))
    misses = int(stats.get("misses", 0))
    total = hits + misses
    return {
        "entries": int(stats.get("entries", 0)
                       if entries is None else entries),
        "size_bytes": int(stats.get("bytes", 0) if size is None else size),
        "capacity_bytes": int(stats.get("capacity", 0)),
        "hits": hits, "misses": misses,
        "hit_ratio": round(hits / total, 3) if total else None,
    }


def info(engine) -> dict:
    """nodetool info: storage totals + key/row/chunk cache hit ratios
    (the reference prints 'Key Cache : entries …, hits …, requests …'
    lines; the caches were invisible outside vtables before)."""
    tables = {}
    for cfs in engine.stores.values():
        tables[cfs.table.full_name()] = {
            "sstables": len(cfs.live_sstables()),
            "memtable_cells": len(cfs.memtable),
            "disk_bytes": sum(s.size_bytes for s in cfs.live_sstables()),
        }
    from ..storage import chunk_cache, key_cache, row_cache
    key = _cache_line(key_cache.GLOBAL.stats(), size=0)
    # the key cache is entry-bounded, not byte-bounded
    key["capacity_entries"] = key.pop("capacity_bytes")
    row = row_cache.GLOBAL.stats()
    # hit/miss per THIS engine's table handles; bytes/capacity are the
    # shared service's (one process-wide row cache)
    row_hits = sum(cfs.row_cache.hits for cfs in engine.stores.values()
                   if cfs.row_cache is not None)
    row_miss = sum(cfs.row_cache.misses for cfs in engine.stores.values()
                   if cfs.row_cache is not None)
    row_entries = sum(len(cfs.row_cache)
                      for cfs in engine.stores.values()
                      if cfs.row_cache is not None)
    row.update({"hits": row_hits, "misses": row_miss})
    # speculative retry visibility (the reference prints 'Speculative
    # Retries' per table in tablestats; here the coordinator-wide
    # fired/won pair): fired = redundant requests issued after the
    # speculative delay, won = those whose response completed the read
    # round (ack rank <= blockFor) — fired >> won means the delay floor
    # is too twitchy, won ~ fired means replicas genuinely straggle
    from ..service.metrics import GLOBAL as _METRICS
    return {"tables": tables, "caches": {
        "key": key,
        "row": _cache_line(row, entries=row_entries),
        "chunk": _cache_line(chunk_cache.GLOBAL.stats()),
    }, "requests": {
        "speculative_retries":
            _METRICS.counter("reads.speculative_retries"),
        "speculative_retries_won":
            _METRICS.counter("reads.speculative_retries_won"),
    }}


def flush(engine, keyspace: str | None = None,
          table: str | None = None) -> int:
    n = 0
    for cfs in list(engine.stores.values()):
        if keyspace and cfs.table.keyspace != keyspace:
            continue
        if table and cfs.table.name != table:
            continue
        if cfs.flush() is not None:
            n += 1
    return n


def compact(engine, keyspace: str | None = None,
            table: str | None = None) -> list[dict]:
    """nodetool compact: major compaction."""
    out = []
    for cfs in list(engine.stores.values()):
        if keyspace and cfs.table.keyspace != keyspace:
            continue
        if table and cfs.table.name != table:
            continue
        stats = engine.compactions.major_compaction(cfs)
        if stats is not None:
            out.append(stats)
    return out


def compactionstats(engine) -> dict:
    """nodetool compactionstats: pending count + per-task live progress
    (ActiveCompactions / CompactionManager.getMetrics in the reference;
    history moved to `compactionhistory`)."""
    cm = engine.compactions
    ex = cm.executor.stats()
    return {
        "pending_tasks": cm.pending_tasks(),
        "active_tasks": ex["active"],
        "concurrent_compactors": ex["concurrent"],
        "throughput_mib_per_sec": cm.limiter.mib_per_s,
        "completed_tasks": len(cm.completed),
        "active_compactions": cm.active.snapshot(),
    }


def commitlogstats(engine) -> dict:
    """nodetool commitlogstats: segment inventory + group-commit health
    (the reference surfaces CommitLogMetrics — waitingOnCommit,
    waitingOnSegmentAllocation, pending/completed tasks — via JMX; here
    the same numbers come from CommitLog.stats() and the
    commitlog.waiting_on_commit / commitlog.sync_latency histograms)."""
    cl = engine.commitlog
    if cl is None:
        return {"enabled": False}
    from ..service.metrics import GLOBAL
    st = cl.stats()
    st.pop("files", None)
    return {
        "enabled": True,
        **st,
        "group_window_ms": cl.group_window_ms,
        "waiting_on_commit_us":
            GLOBAL.hist("commitlog.waiting_on_commit").summary(),
        "sync_latency_us":
            GLOBAL.hist("commitlog.sync_latency").summary(),
    }


def tablestats(engine, keyspace: str | None = None) -> dict:
    """nodetool tablestats: per-table live-set stats plus the
    amplification accounting block — the observed byte counters
    (ingested/flushed/compacted in+out) and the derived
    write/space-amplification gauges the adaptive-compaction loop
    reads (storage/table.py amplification())."""
    out = {}
    for cfs in engine.stores.values():
        t = cfs.table
        if keyspace and t.keyspace != keyspace:
            continue
        live = cfs.live_sstables()
        amp = cfs.amplification()
        out[t.full_name()] = {
            "sstable_count": len(live),
            "space_used_bytes": sum(s.size_bytes for s in live),
            "cells": sum(s.n_cells for s in live),
            "partitions_estimate": sum(s.n_partitions for s in live),
            "tombstones": sum(s.n_tombstones for s in live),
            "memtable_cells": len(cfs.memtable),
            "reads": cfs.metrics["reads"],
            "writes": cfs.metrics["writes"],
            "flushes": cfs.metrics["flushes"],
            "bytes_ingested": cfs.metrics.get("bytes_ingested", 0),
            "bytes_flushed": cfs.metrics.get("bytes_flushed", 0),
            "bytes_compacted_in":
                cfs.metrics.get("bytes_compacted_in", 0),
            "bytes_compacted_out":
                cfs.metrics.get("bytes_compacted_out", 0),
            "write_amplification": amp["write_amplification"],
            "space_amplification": amp["space_amplification"],
            "sstables_per_read_p99":
                cfs.sstables_per_read.percentile(0.99),
            "row_cache": (None if cfs.row_cache is None
                          else {"hits": cfs.row_cache.hits,
                                "misses": cfs.row_cache.misses,
                                "entries": len(cfs.row_cache)}),
        }
    return out


def repair(node, keyspace: str, table: str | None = None,
           full: bool = False, preview: bool = False) -> list[dict]:
    """nodetool repair — incremental by default: validation still covers
    the FULL data set (unrepaired-only trees diverge once repaired
    status differs across replicas), but afterwards the validated
    unrepaired sstables are ANTICOMPACTED and stamped repairedAt so the
    compaction split applies; --full skips the stamping entirely."""
    out = []
    ks = node.schema.keyspaces[keyspace]
    for name in ([table] if table else list(ks.tables)):
        out.append({"table": f"{keyspace}.{name}",
                    **node.repair.repair_table(keyspace, name,
                                               incremental=not full,
                                               preview=preview)})
    return out


def cleanup(node, keyspace: str | None = None,
            table: str | None = None) -> list[dict]:
    """nodetool cleanup: rewrite sstables dropping cells for token
    ranges this node no longer replicates (post-bootstrap/move data
    reclamation — CompactionManager.performCleanup role)."""
    import numpy as np

    from ..cluster.replication import ReplicationStrategy
    from ..storage.cellbatch import (CellBatch, batch_tokens,
                                     token_range_mask)
    from ..storage.rewrite import rewrite_sstable
    out = []
    engine = node.engine
    for cfs in list(engine.stores.values()):
        t = cfs.table
        if keyspace and t.keyspace != keyspace:
            continue
        if table and t.name != table:
            continue
        ksm = node.schema.keyspaces.get(t.keyspace)
        if ksm is None:
            continue
        strat = ReplicationStrategy.create(ksm.params.replication)
        owned = []
        for lo, hi in node.ring.all_ranges():
            if node.endpoint in strat.replicas(node.ring, hi):
                if lo == hi:               # single-token ring: the one
                    owned.append((-(1 << 63), (1 << 63) - 1))  # arc IS
                elif lo <= hi:                         # the full ring
                    owned.append((lo, hi))
                else:                      # wrap arc
                    owned.append((-(1 << 63), hi))
                    owned.append((lo, (1 << 63) - 1))
        with engine.compactions.cfs_lock(cfs):
            for sst in list(cfs.live_sstables()):
                segs = list(sst.scanner())
                if not segs:
                    continue
                cat = CellBatch.concat(segs)
                cat.sorted = True
                keep = token_range_mask(batch_tokens(cat), owned)
                dropped = int((~keep).sum())
                if dropped == 0:
                    continue

                def fill(w, cat=cat, keep=keep):
                    idx = np.flatnonzero(keep)
                    if len(idx):
                        part = cat.apply_permutation(idx)
                        part.sorted = True
                        w.append(part)

                rewrite_sstable(cfs, sst,
                                [(sst.repaired_at, sst.level, fill)])
                out.append({"table": t.full_name(),
                            "generation": sst.desc.generation,
                            "cells_dropped": dropped})
    return out


def getendpoints(node, keyspace: str, table: str, key: str) -> list[str]:
    """nodetool getendpoints: replicas for a partition key. Values are
    converted by the COLUMN TYPE (never guessed from the text — a text
    key '7' must not tokenize as an int), and composite partition keys
    take ':'-separated components so the token matches the write path's
    composite framing."""
    from ..cluster.replication import ReplicationStrategy
    from .copyutil import _parse_value
    t = node.schema.get_table(keyspace, table)
    cols = t.partition_key_columns
    parts = key.split(":") if len(cols) > 1 else [key]
    if len(parts) != len(cols):
        raise ValueError(
            f"partition key of {keyspace}.{table} has {len(cols)} "
            f"components ({', '.join(c.name for c in cols)}); pass them "
            "':'-separated")
    vals = [_parse_value(p, c.cql_type) for p, c in zip(parts, cols)]
    pk = t.serialize_partition_key(vals)
    strat = ReplicationStrategy.create(
        node.schema.keyspaces[keyspace].params.replication)
    return [e.name for e in strat.replicas(node.ring,
                                           node.ring.token_of(pk))]


def gossipinfo(node) -> dict:
    """nodetool gossipinfo."""
    out = {}
    for ep, st in node.gossiper.states.items():
        out[ep.name] = {"generation": st.generation,
                        "version": st.version,
                        "alive": bool(st.alive),
                        "app_states": dict(st.app_states)}
    return out


def version(engine=None) -> dict:
    """nodetool version."""
    return {"release": "cassandra-tpu 2.0", "cql": "3.4.5",
            "sstable_format": "ctpu/ca"}


def describecluster(node) -> dict:
    """nodetool describecluster."""
    return {
        "name": "cassandra_tpu",
        "partitioner": "Murmur3Partitioner",
        "endpoints": [e.name for e in node.ring.endpoints],
        "schema_epoch": getattr(getattr(node, "schema_sync", None),
                                "epoch", None),
        # topology rides the same epoch log (TCM): the metadata epoch IS
        # the schema_sync epoch; kept as a separate key for operators
        "metadata_epoch": getattr(getattr(node, "schema_sync", None),
                                  "epoch", None),
        "pending_joins": [e.name for e in node.ring.pending],
        "replacing": {n.name: d.name
                      for n, d in node.ring.replacing.items()},
    }


def setcompactionthroughput(engine, mib_s: int) -> dict:
    """nodetool setcompactionthroughput (0 = unthrottled). Sets BOTH
    knob spellings so the modern name's precedence can never shadow an
    operator command. Routed through
    the mutable settings surface so the settings vtable, listeners and
    the limiter stay consistent."""
    engine.settings.set("compaction_throughput", float(mib_s))
    engine.settings.set("compaction_throughput_mib_per_sec", float(mib_s))
    return {"compaction_throughput_mib": mib_s}


def getcompactionthroughput(engine) -> dict:
    """nodetool getcompactionthroughput."""
    return {"compaction_throughput_mib":
            int(engine.compactions.limiter.rate // 2**20)}


def setslowquerythreshold(engine, ms: float) -> dict:
    """slow_query_log_timeout_in_ms knob (db/monitoring role)."""
    engine.monitor.threshold_ms = float(ms)
    return {"slow_query_threshold_ms": float(ms)}


def upgradesstables(engine, keyspace: str | None = None,
                    table: str | None = None) -> list[dict]:
    """nodetool upgradesstables: rewrite every sstable in the current
    format (compaction/Upgrader role — after a format revision, old
    generations are re-serialized through the current writer)."""
    from ..storage.rewrite import rewrite_sstable
    out = []
    for cfs in list(engine.stores.values()):
        if keyspace and cfs.table.keyspace != keyspace:
            continue
        if table and cfs.table.name != table:
            continue
        with engine.compactions.cfs_lock(cfs):
            for sst in list(cfs.live_sstables()):
                def fill(w, sst=sst):
                    for i in range(sst.n_segments):
                        w.append(sst._read_segment(i))

                new = rewrite_sstable(
                    cfs, sst, [(sst.repaired_at, sst.level, fill)])
                out.append({"table": cfs.table.full_name(),
                            "from_generation": sst.desc.generation,
                            "to_generation":
                                new[0].desc.generation if new else None})
    return out


def sstablesplit(engine, keyspace: str, table: str,
                 target_mib: int = 50) -> list[dict]:
    """SSTableSplitter role: carve an oversized sstable into ~target
    sized outputs, split at partition boundaries."""
    import numpy as np

    from ..storage.cellbatch import CellBatch
    from ..storage.rewrite import rewrite_sstable
    cfs = engine.store(keyspace, table)
    target = max(1, target_mib * 2**20)
    out = []
    with engine.compactions.cfs_lock(cfs):
        for sst in list(cfs.live_sstables()):
            if sst.data_size <= target:
                continue
            n_parts = min(64, max(2, -(-sst.data_size // target)))
            segs = list(sst.scanner())
            if not segs:
                continue
            cat = CellBatch.concat(segs)
            cat.sorted = True
            # partition boundaries: first cell of each partition (the
            # token+pkh lanes change)
            keys = cat.lanes[:, 0].astype(np.uint64) << np.uint64(32) \
                | cat.lanes[:, 1]
            starts = np.flatnonzero(np.diff(keys) != 0) + 1
            cuts = [0]
            for p in range(1, n_parts):
                want = p * len(cat) // n_parts
                j = int(np.searchsorted(starts, want))
                cut = int(starts[j]) if j < len(starts) else len(cat)
                if cut > cuts[-1]:
                    cuts.append(cut)
            cuts.append(len(cat))

            def fill_for(lo, hi, cat=cat):
                def fill(w):
                    part = cat.slice_range(lo, hi)
                    part.sorted = True
                    w.append(part)
                return fill

            parts = [(sst.repaired_at, sst.level, fill_for(lo, hi))
                     for lo, hi in zip(cuts, cuts[1:]) if hi > lo]
            new = rewrite_sstable(cfs, sst, parts)
            out.append({"table": cfs.table.full_name(),
                        "generation": sst.desc.generation,
                        "outputs": [r.desc.generation for r in new]})
    return out


def ring(node) -> list[dict]:
    out = []
    for ep, toks in sorted(node.ring.endpoints.items(),
                           key=lambda kv: kv[0].name):
        for t in sorted(toks):
            out.append({"token": t, "endpoint": ep.name})
    return out


def snapshot(engine, keyspace: str | None = None,
             table: str | None = None, tag: str | None = None) -> list[str]:
    """nodetool snapshot."""
    from ..storage import snapshot as snap
    out = []
    for cfs in engine.stores.values():
        if keyspace and cfs.table.keyspace != keyspace:
            continue
        if table and cfs.table.name != table:
            continue
        cfs.flush()   # snapshots must include memtable contents
        out.append(f"{cfs.table.full_name()}:{snap.snapshot(cfs, tag)}")
    return out


def listsnapshots(engine) -> list[dict]:
    from ..storage import snapshot as snap
    out = []
    for cfs in engine.stores.values():
        out.extend(snap.list_snapshots(cfs))
    return out


def clearsnapshot(engine, tag: str | None = None) -> int:
    from ..storage import snapshot as snap
    return sum(snap.clear_snapshot(cfs, tag)
               for cfs in engine.stores.values())


def scrub(engine, keyspace: str | None = None,
          table: str | None = None, snapshot_before: bool = True,
          quarantine: bool = False) -> list[dict]:
    """nodetool scrub: rewrite each sstable keeping every readable
    segment, dropping corrupt ones (io/sstable/format/
    SortedTableScrubber role). The unreadable cells are gone either way;
    scrub turns a read-aborting sstable into a clean one.

    snapshot_before: hardlink the whole live set into a
    `pre-scrub-<ts>` snapshot first (the reference's
    snapshot-before-scrub — scrub is lossy by design, so the originals
    stay recoverable). quarantine: an sstable too rotten to rewrite at
    all (index/open-level corruption, I/O errors) moves into the
    quarantine set instead of staying live and aborting the scrub."""
    import time as _time

    from ..storage import snapshot as snap
    from ..storage.rewrite import rewrite_sstable
    from ..storage.sstable.reader import CorruptSSTableError
    out = []
    for cfs in list(engine.stores.values()):
        if keyspace and cfs.table.keyspace != keyspace:
            continue
        if table and cfs.table.name != table:
            continue
        with engine.compactions.cfs_lock(cfs):
            tag = None
            if snapshot_before and cfs.live_sstables():
                tag = f"pre-scrub-{int(_time.time() * 1000)}"
                snap.snapshot(cfs, tag)
            for sst in list(cfs.live_sstables()):
                counts = {"kept": 0, "dropped": 0}

                def fill(w, sst=sst, counts=counts):
                    for i in range(sst.n_segments):
                        try:
                            seg = sst._read_segment(i)
                        except CorruptSSTableError:
                            counts["dropped"] += 1
                            continue
                        w.append(seg)
                        counts["kept"] += 1

                try:
                    rewrite_sstable(cfs, sst,
                                    [(sst.repaired_at, sst.level, fill)])
                except (CorruptSSTableError, OSError) as e:
                    if not quarantine:
                        raise
                    cfs.failures.handle(e, sst.desc.path("Data.db"))
                    cfs.quarantine_sstable(sst, e)
                    out.append({"table": cfs.table.full_name(),
                                "generation": sst.desc.generation,
                                "quarantined": True, "error": str(e),
                                "snapshot": tag})
                    continue
                out.append({"table": cfs.table.full_name(),
                            "generation": sst.desc.generation,
                            "segments_kept": counts["kept"],
                            "segments_dropped": counts["dropped"],
                            "snapshot": tag})
    return out


def garbagecollect(engine, keyspace: str | None = None,
                   table: str | None = None) -> list[dict]:
    """Single-sstable rewrite dropping gc-able tombstones
    (nodetool garbagecollect)."""
    from ..compaction.task import CompactionTask
    out = []
    for cfs in list(engine.stores.values()):
        if keyspace and cfs.table.keyspace != keyspace:
            continue
        if table and cfs.table.name != table:
            continue
        with engine.compactions.cfs_lock(cfs):
            for sst in list(cfs.live_sstables()):
                out.append(CompactionTask(cfs, [sst]).execute())
    return out


# ------------------------------------------------- round-3 command set --

def netstats(node) -> dict:
    """nodetool netstats: live sessioned-transfer progress (chunks and
    bytes, mid-flight), terminal session summaries, internode counters."""
    from ..storage.virtual import _snapshot
    svc = getattr(node, "streams", None)
    live = svc.progress() if svc is not None \
        and hasattr(svc, "progress") else []
    return {"streams": live,
            "streaming": _snapshot(getattr(node.streams, "sessions", [])),
            "messaging": dict(node.messaging.metrics)}


def tpstats(engine) -> list[dict]:
    """nodetool tpstats (thread_pools vtable data)."""
    cm = engine.compactions
    ex = cm.executor.stats()
    return [{"pool": "CompactionExecutor",
             "active": ex["active"],
             "pending": cm.pending_tasks(),
             # compactions actually executed (agrees with
             # compactionstats.completed_tasks), not executor callables
             "completed": len(cm.completed)},
            {"pool": "MemtableFlushWriter", "active": 0, "pending": 0,
             "completed": sum(cfs.metrics.get("flushes", 0)
                              for cfs in engine.stores.values())}]


def proxyhistograms(node) -> dict:
    """nodetool proxyhistograms: coordinator-side latency percentiles."""
    from ..service.metrics import GLOBAL
    s = GLOBAL.hist("cql.request").summary()   # one consistent read
    with node.proxy._lat_lock:
        lat = dict(node.proxy._latency)
    return {"request": {"p50_us": s["p50_us"],
                        "p95_us": s["p95_us"],
                        "p99_us": s["p99_us"],
                        "count": s["count"]},
            "replica_ewma_ms": {ep.name: round(v * 1000, 3)
                                for ep, v in lat.items()}}


def compactionhistory(engine) -> list[dict]:
    """nodetool compactionhistory."""
    from ..storage.virtual import _snapshot
    out = []
    for cfs in engine.stores.values():
        # bounded deque: copy before iterating (a finishing compaction
        # appends concurrently)
        for st in _snapshot(cfs.compaction_history):
            out.append({"table": cfs.table.full_name(), **st})
    return out


def clientstats(node) -> list[dict]:
    """nodetool clientstats: connected native-protocol clients
    (ClientsTable role: address, protocol version, requests served,
    in-flight on the dispatch executor, requests shed by the per-client
    rate limiter)."""
    out = []
    for srv in getattr(node, "cql_servers", []):
        for info in list(srv.clients.values()):
            conn = info["conn"]
            out.append({"id": info["id"], "address": info["address"],
                        "user": conn.user or "anonymous",
                        "keyspace": conn.keyspace or "",
                        "version": conn.version or 0,
                        "requests": info["requests"],
                        "in_flight": conn.in_flight,
                        "rate_limited": conn.rate_limited})
    return out


def gettimeout(node, timeout_type: str = "read") -> dict:
    """nodetool gettimeout <read|write|range>."""
    attr = {"read": "read_timeout", "write": "write_timeout",
            "range": "range_timeout"}[timeout_type]
    return {timeout_type: getattr(node.proxy, attr) * 1000.0}


def settimeout(node, timeout_type: str, ms: float) -> dict:
    """nodetool settimeout <read|write|range> <ms> (through settings)."""
    name = {"read": "read_request_timeout",
            "write": "write_request_timeout",
            "range": "range_request_timeout"}[timeout_type]
    node.engine.settings.set(name, f"{int(ms)}ms")
    return gettimeout(node, timeout_type)


def getstreamthroughput(engine) -> dict:
    return {"stream_throughput_mib":
            engine.settings.get("stream_throughput_outbound")}


def setstreamthroughput(engine, mib_s: float) -> dict:
    engine.settings.set("stream_throughput_outbound", float(mib_s))
    return getstreamthroughput(engine)


def getconcurrentcompactors(engine) -> dict:
    return {"concurrent_compactors":
            engine.settings.get("concurrent_compactors")}


def setconcurrentcompactors(engine, n: int) -> dict:
    """nodetool setconcurrentcompactors: validated here so the settings
    surface can never report a value the executor silently clamps
    (DatabaseDescriptor.setConcurrentCompactors rejects < 1 too)."""
    if int(n) < 1:
        raise ValueError(f"concurrent_compactors must be >= 1, got {n}")
    engine.settings.set("concurrent_compactors", int(n))
    return getconcurrentcompactors(engine)


def gettraceprobability(engine) -> dict:
    return {"trace_probability": engine.settings.get("trace_probability")}


def settraceprobability(engine, p: float) -> dict:
    """nodetool settraceprobability: sample rate for background request
    tracing — Session.execute consults it via tracing.should_sample();
    sampled statements land in the engine's TraceStore
    (system_traces.sessions / `nodetool gettraces`)."""
    if not 0.0 <= float(p) <= 1.0:
        raise ValueError(f"trace probability must be in [0, 1], got {p}")
    engine.settings.set("trace_probability", float(p))
    return gettraceprobability(engine)


def gettraces(engine, limit: int = 20) -> list[dict]:
    """nodetool gettraces: recent completed trace sessions with their
    merged coordinator+replica timelines (system_traces role)."""
    out = []
    for st in engine.trace_store.sessions()[-int(limit):]:
        out.append({
            "session_id": st.session_id,
            "request": st.request,
            "started_at_ms": int(st.started_at * 1000),
            "duration_us": st.duration_us,
            "events": [{"elapsed_us": us, "source": src,
                        "activity": activity}
                       for us, src, activity in list(st.events)],
        })
    return out


def exportmetrics(engine) -> str:
    """nodetool exportmetrics: the full registry in Prometheus
    exposition format (counters, gauges, decayed latency summaries) plus
    this engine's compaction gauges."""
    from ..service.metrics import prometheus_text
    return prometheus_text(extra_gauges=engine.compactions.gauges())


def diagnostics(engine, limit: int = 50,
                event_type: str | None = None) -> dict:
    """nodetool diagnostics: recent typed diagnostic events from the
    bus (diag/DiagnosticEventService role). Empty until the mutable
    `diagnostic_events_enabled` knob flips on."""
    from ..service import diagnostics as diag
    return {"enabled": diag.GLOBAL.enabled,
            "types": diag.GLOBAL.types(),
            "events": [e.to_dict() for e in
                       diag.GLOBAL.events(event_type,
                                          limit=int(limit))]}


def flightrecorder(engine, action: str = "dump") -> dict:
    """nodetool flightrecorder [dump|status]: the black box. `dump`
    writes a self-contained JSON bundle (diagnostic events, metric +
    tpstats snapshot ring, recent traces, failure state, settings)
    under <data_dir>/diagnostics/ — the same bundle a failure policy
    (stop/die/stop_commit) or a quarantine dumps automatically."""
    rec = engine.flight_recorder
    if action == "status":
        return {"events_buffered": len(rec._events),
                "snapshots_buffered": len(rec._snapshots),
                "dumps": list(rec.dumps)}
    if action != "dump":
        raise ValueError(f"unknown flightrecorder action {action!r}")
    path = rec.dump("on_demand")
    return {"bundle": path}


def slostats(engine) -> dict:
    """nodetool slostats: per-objective SLO state — current p99 vs
    target, error budget remaining, breach/exhaustion tallies. Runs a
    REAL `check()` (budgets burn/replenish, a live breach publishes
    `slo.breach` and dumps a deduplicated flight-recorder bundle), so
    the operator asking for slostats gets the current verdict, not the
    last poll's; the `system_views.slos` vtable is the side-effect-free
    view."""
    svc = engine.slo
    return {"objectives": svc.check(),
            "checks": svc.checks,
            "recorder_dumps": list(getattr(svc.recorder, "dumps", []))}


def pipelinestats(engine) -> dict:
    """nodetool pipelinestats: the unified pipeline ledger — per-stage
    busy/stall/idle seconds, items/bytes and queue high-water for every
    multi-stage pipeline (utils/pipeline_ledger.py; the
    system_views.pipelines vtable serves the same rows)."""
    from ..utils import pipeline_ledger
    return pipeline_ledger.snapshot_all()


def metricshistory(engine, name: str | None = None,
                   resolution: str = "raw",
                   limit: int = 50, rate: bool = False) -> dict:
    """nodetool metricshistory [name=<metric>] [resolution=raw|coarse]
    [limit=N] [rate=true]: the retained metrics time series
    (service/history.py). Without `name`, lists the series and the
    sampler state; with it, returns the newest `limit` buckets (and
    the derived per-second counter rate when rate=true). The
    system_views.metrics_history vtable serves the same rows."""
    svc = engine.metrics_history
    if name is None:
        return {**svc.stats(), "series_names": svc.names()}
    out = {"name": name, "resolution": resolution,
           "buckets": svc.query(name, resolution, limit=int(limit))}
    if rate:
        out["rate_per_s"] = svc.rate(name, limit=int(limit))
    return out


def profiler(engine, action: str = "status",
             session: str | None = None, limit: int = 50) -> dict:
    """nodetool profiler [start|stop|dump|status]: the continuous
    wall-clock profiler (service/sampler.py) + device program registry
    (service/profiling.py) — observability layer 6.

    - start [session=<name>]: open an on-demand profiling window (the
      sampler thread boots even with `profiler_enabled` off);
    - stop [session=<id>]: seal a window (newest if unnamed) and
      return its cpu/blocked split;
    - dump [session=<id>] [limit=N]: the collapsed-stack flamegraph
      (hottest first) + split of a session, or of the always-on ring
      when no session is named — feed the lines to flamegraph.pl
      as-is;
    - status: sampler state + the per-program compile/dispatch/execute
      registry (the system_views.profiles / device_programs vtables
      serve the same)."""
    from ..service import profiling as _profiling
    from ..service import sampler as _sampler
    sp = _sampler.GLOBAL
    if action == "start":
        sid = sp.start_session(name=session)
        return {"session": sid, "running": sp.running,
                "interval_s": sp.interval_s}
    if action == "stop":
        return sp.stop_session(session)
    if action == "dump":
        target = session or "ring"
        return {"target": target,
                "split": sp.split(target),
                "flamegraph": sp.collapsed(target, limit=int(limit))}
    if action == "status":
        return {**sp.stats(),
                "retrace_budget": _profiling.GLOBAL.retrace_budget,
                "device_programs":
                    _profiling.GLOBAL.snapshot()["kernels"]}
    raise ValueError(
        f"unknown profiler action {action!r} (start|stop|dump|status)")


def clusterstats(node, timeout: float = 2.0) -> dict:
    """nodetool clusterstats: the one-screen RF-aware cluster view —
    every peer's telemetry snapshot pulled over the METRICS_SNAPSHOT
    verb (local node served directly), with per-node staleness stamps:
    a dark node's row carries its LAST known snapshot and how stale it
    is, never a hang (the pull is bounded by `timeout`)."""
    pulled = node.pull_cluster_telemetry(timeout=float(timeout))
    keyspaces = {}
    for ksname, ks in node.schema.keyspaces.items():
        rep = dict(getattr(ks.params, "replication", {}) or {})
        rf = rep.get("replication_factor")
        keyspaces[ksname] = {
            "replication": rep,
            "rf": int(rf) if rf is not None else None,
        }
    screen = []
    for row in pulled["nodes"]:
        snap = row.get("snapshot") or {}
        tabs = snap.get("tables", {})
        wa = {t: v.get("write_amplification") for t, v in tabs.items()}
        screen.append(
            f"{row['endpoint']:>8} "
            f"{'UP' if row['alive'] else 'DOWN':>4} "
            f"stale={'-' if row['stale_s'] is None else round(row['stale_s'], 2)} "
            f"writes={snap.get('storage_writes', '-')} "
            f"pending_compactions={snap.get('compactions', {}).get('compaction.pending_tasks', '-')} "
            f"wa={wa}")
    return {"nodes": pulled["nodes"], "keyspaces": keyspaces,
            "ring_size": len(node.ring.endpoints),
            "screen": screen}


def disableautocompaction(engine) -> dict:
    """nodetool disableautocompaction (pauses the background worker's
    submissions; running tasks finish)."""
    engine.compactions.paused = True
    return {"auto_compaction": "disabled"}


def enableautocompaction(engine) -> dict:
    engine.compactions.paused = False
    return {"auto_compaction": "enabled"}


def statusautocompaction(engine) -> dict:
    return {"running": not getattr(engine.compactions, "paused", False)}


def autocompaction(engine, action: str = "status",
                   limit: int = 20) -> dict:
    """nodetool autocompaction [status|history|freeze|unfreeze]: the
    adaptive compaction controller surface (control/loop.py).

    - status: loop/frozen state, tick/decision counters and every
      table's current regime + recent-window signals;
    - history: the newest `limit` rows of the bounded decision ledger
      (the system_views.controller_decisions vtable serves the same);
    - freeze / unfreeze: keep the loop ticking but apply NOTHING —
      persisted under the data dir, so the freeze survives an engine
      restart."""
    ctrl = engine.controller
    if action == "status":
        return {**ctrl.stats(), "tables": ctrl.table_regimes()}
    if action == "history":
        return {"decisions": ctrl.decisions(limit=int(limit))}
    if action == "freeze":
        ctrl.freeze()
        return {"controller": "frozen"}
    if action == "unfreeze":
        ctrl.unfreeze()
        return {"controller": "unfrozen"}
    raise ValueError(
        f"unknown autocompaction action {action!r} "
        f"(status|history|freeze|unfreeze)")


def disablehandoff(node) -> dict:
    """nodetool disablehandoff: stop storing new hints."""
    node.hints.enabled = False
    return {"handoff": "disabled"}


def enablehandoff(node) -> dict:
    node.hints.enabled = True
    return {"handoff": "enabled"}


def statushandoff(node) -> dict:
    return {"handoff": "running"
            if getattr(node.hints, "enabled", True) else "disabled"}


def truncatehints(node, endpoint: str | None = None) -> dict:
    """nodetool truncatehints [endpoint] — delegates to
    HintsService.truncate, which holds the service lock so a concurrent
    store()/dispatch() can't race the deletes."""
    return {"truncated_files": node.hints.truncate(endpoint)}


def statusgossip(node) -> dict:
    return {"gossip": "running" if node.gossiper.is_running()
            else "not running"}


def statusbinary(node) -> dict:
    return {"native_transport": "running"
            if getattr(node, "cql_servers", []) else "not running"}


def drain(node) -> dict:
    """nodetool drain: flush everything, stop accepting new compactions;
    the commitlog is synced so restart replays nothing."""
    node.engine.compactions.paused = True
    node.engine.flush_all()
    if node.engine.commitlog is not None:
        node.engine.commitlog.sync()
    return {"drained": True}


def refresh(engine, keyspace: str, table: str) -> dict:
    """nodetool refresh: pick up sstables dropped into the data dir
    out-of-band (bulk load path)."""
    cfs = engine.store(keyspace, table)
    before = len(cfs.live_sstables())
    cfs.reload_sstables()
    return {"sstables_before": before,
            "sstables_after": len(cfs.live_sstables())}


def invalidaterowcache(engine) -> dict:
    n = 0
    for cfs in engine.stores.values():
        if cfs.row_cache is not None:
            cfs.row_cache.clear()
            n += 1
    return {"invalidated_tables": n}


def invalidatechunkcache(engine) -> dict:
    from ..storage import chunk_cache
    chunk_cache.GLOBAL.clear()
    return {"invalidated": True}


def invalidatecountercache(node) -> dict:
    node.counters.invalidate_cache()
    return {"invalidated": True}


def getsstables(engine, keyspace: str, table: str, key: str) -> list[str]:
    """nodetool getsstables: which sstables hold a partition key."""
    from .copyutil import _parse_value
    t = engine.store(keyspace, table).table
    cols = t.partition_key_columns
    parts = key.split(":") if len(cols) > 1 else [key]
    vals = [_parse_value(p, c.cql_type) for p, c in zip(parts, cols)]
    pk = t.serialize_partition_key(vals)
    cfs = engine.store(keyspace, table)
    out = []
    for sst in cfs.live_sstables():
        if sst.might_contain(pk):
            out.append(f"{sst.desc.version}-{sst.desc.generation}")
    return out


def verify(engine, keyspace: str | None = None,
           table: str | None = None,
           quarantine: bool = False) -> list[dict]:
    """nodetool verify: recheck each sstable's digest against its data.
    quarantine=True hands every failing sstable to the quarantine set
    (the --quarantine handoff: a failed verify must not leave a known-
    corrupt file live)."""
    from ..storage.sstable.reader import CorruptSSTableError
    out = []
    for cfs in list(engine.stores.values()):
        t = cfs.table
        if keyspace and t.keyspace != keyspace:
            continue
        if table and t.name != table:
            continue
        for sst in list(cfs.live_sstables()):
            entry = {"sstable": sst.desc.generation,
                     "table": t.full_name()}
            try:
                ok = sst.verify_digest()
            except Exception as e:
                ok = False
                entry["error"] = str(e)
            entry["ok"] = bool(ok)
            if not ok and quarantine:
                err = CorruptSSTableError(
                    f"{sst.desc}: verify failed", descriptor=sst.desc)
                cfs.failures.handle_corruption(
                    err, sst.desc.path("Data.db"))
                cfs.quarantine_sstable(sst, err)
                entry["quarantined"] = True
            out.append(entry)
    return out


def assassinate(node, endpoint: str) -> dict:
    """nodetool assassinate: force-convict an endpoint without waiting
    for phi (Gossiper.assassinateEndpoint role)."""
    for ep in node.ring.endpoints:
        if ep.name == endpoint:
            node.gossiper.force_convict(ep)
            return {"assassinated": endpoint}
    raise ValueError(f"unknown endpoint {endpoint!r}")


def listquarantine(engine, keyspace: str | None = None,
                   table: str | None = None) -> list[dict]:
    """nodetool listquarantine: corrupt sstables blacklisted out of the
    live set (the quarantined_sstables vtable's data, per table)."""
    out = []
    for cfs in engine.stores.values():
        if keyspace and cfs.table.keyspace != keyspace:
            continue
        if table and cfs.table.name != table:
            continue
        for q in list(getattr(cfs, "quarantined", [])):
            out.append({"table": cfs.table.full_name(),
                        "generation": q["generation"],
                        "reason": q.get("reason", ""),
                        "bytes": q.get("bytes", 0),
                        "path": q.get("path", "")})
    return out


def listpendinghints(node) -> list[dict]:
    import os as _os
    out = []
    d = node.hints.directory
    for fn in sorted(_os.listdir(d)):
        if fn.startswith("hints-"):
            out.append({"target": fn[len("hints-"):-3],
                        "bytes": _os.path.getsize(_os.path.join(d, fn))})
    return out


def getlogginglevels() -> dict:
    import logging
    return {name: logging.getLevelName(logging.getLogger(name).level)
            for name in sorted(logging.root.manager.loggerDict)
            if name.startswith("cassandra_tpu")} or \
        {"root": logging.getLevelName(logging.root.level)}


def setlogginglevel(logger: str = "root", level: str = "INFO") -> dict:
    import logging
    lg = logging.root if logger == "root" else logging.getLogger(logger)
    lg.setLevel(level.upper())
    return {logger: level.upper()}


def updatecidrgroup(engine, name: str, cidrs) -> dict:
    """nodetool updatecidrgroup <name> <cidrs> — define/replace a named
    CIDR group (auth/CIDRPermissionsManager)."""
    if isinstance(cidrs, str):
        cidrs = [c.strip() for c in cidrs.split(",") if c.strip()]
    engine.auth.set_cidr_group(name, cidrs)
    return {name: cidrs}


def dropcidrgroup(engine, name: str) -> dict:
    engine.auth.drop_cidr_group(name)
    return {"dropped": name}


def listcidrgroups(engine) -> dict:
    return dict(engine.auth.cidr_groups)


def invalidatecredentialscache(engine) -> dict:
    """nodetool invalidatecredentialscache / invalidatepermissionscache:
    drop all AuthCache verdicts."""
    engine.auth.cache.invalidate_all()
    return {"invalidated": True}


def decommission(node) -> dict:
    """nodetool decommission (streams ranges away, leaves the ring)."""
    node.decommission()
    return {"decommissioned": node.endpoint.name}


def move(node, new_token: int) -> dict:
    """nodetool move <token> (TCM Move sequence)."""
    node.move_tokens([int(new_token)])
    return {"moved_to": int(new_token)}


# Registry: name -> (target kind, callable). Target "node" needs the full
# cluster Node; "engine" works on a bare StorageEngine (offline --data
# mode supports only those); "none" needs neither.
def repair_admin(node, list_all: bool = False) -> list[dict]:
    """nodetool repair_admin — durable repair-session records
    (repair/consistent/LocalSessions role): by default the sessions
    still IN_PROGRESS (including ones orphaned by a coordinator crash,
    read back from the journal after restart); --list_all for the full
    history."""
    store = node.repair.sessions
    return store.sessions() if list_all else store.in_flight()


def bulkload(node, directory: str, keyspace: str, table: str) -> dict:
    """nodetool bulkload — ring-aware streaming of externally-written
    sstables into the cluster (tools/BulkLoader.java role; see
    tools/sstableloader.py for the standalone CLI)."""
    from .sstableloader import load
    return load(directory, node, keyspace, table)


def rebuild(node, keyspace: str | None = None) -> dict:
    """nodetool rebuild — re-stream every range this node replicates
    from a surviving replica (tools/nodetool/Rebuild.java): entire
    in-range sstables land as component files, boundary-straddling data
    as merged batches. Used after disk loss or to fill a node that
    joined without bootstrap."""
    from ..cluster.replication import ReplicationStrategy
    MIN, MAX = -(1 << 63), (1 << 63) - 1
    total_files = 0
    total_cells = 0
    ranges_done = 0
    for ks in list(node.schema.keyspaces.values()):
        if keyspace and ks.name != keyspace:
            continue
        if not ks.tables:
            continue
        strat = ReplicationStrategy.create(ks.params.replication)
        for lo, hi in node.ring.all_ranges():
            replicas = strat.replicas(node.ring, hi)
            if node.endpoint not in replicas:
                continue
            sources = [e for e in replicas
                       if e != node.endpoint and node.is_alive(e)]
            if not sources:
                # RF=1 ranges have no other replica; skip silently only
                # when we are the SOLE replica, else surface the outage
                if len(replicas) > 1:
                    raise RuntimeError(
                        f"rebuild: no live source for range ({lo}, {hi}] "
                        f"of {ks.name} (replicas {replicas})")
                continue
            ranges_done += 1
            for tname in ks.tables:
                arcs = [(MIN, hi), (lo, MAX)] if lo > hi else [(lo, hi)]
                for alo, ahi in arcs:
                    res = node.streams.stream_range(
                        sources[0], ks.name, tname, alo, ahi,
                        timeout=max(node.proxy.timeout, 30.0))
                    total_files += int(res["files"])
                    total_cells += int(res["cells"])
    return {"ranges": ranges_done, "files_streamed": total_files,
            "cells_streamed": total_cells}



COMMANDS: dict = {}
# --------------------------------------------------------------------------
# round-5 breadth: the reference's long tail, each wired to real machinery
# (tools/nodetool/*.java counterparts named per function)


def describering(node, keyspace: str) -> list[dict]:
    """nodetool describering: every token range with its endpoints
    (tools/nodetool/DescribeRing.java)."""
    from ..cluster.replication import ReplicationStrategy
    ks = node.schema.keyspaces[keyspace]
    strat = ReplicationStrategy.create(ks.params.replication)
    out = []
    for lo, hi in node.ring.all_ranges():
        out.append({"start_token": lo, "end_token": hi,
                    "endpoints": [e.name for e in
                                  strat.replicas(node.ring, hi)]})
    return out


def cmsadmin(node) -> dict:
    """nodetool cmsadmin describe: CMS membership + epoch state
    (tools/nodetool/CMSAdmin.java over the Paxos-backed CMS)."""
    sync = getattr(node, "schema_sync", None)
    if sync is None:
        return {"cms": None, "reason": "no metadata log on this node"}
    return {"members": [m.name for m in sync.cms_members()],
            "is_member": sync.cms.is_member(),
            "epoch": sync.epoch,
            "log_tail": [(e[0], e[1][:60]) for e in
                         sync.entries_after(max(0, sync.epoch - 5))]}


def failuredetectorinfo(node) -> list[dict]:
    """nodetool failuredetector: per-endpoint phi
    (tools/nodetool/FailureDetectorInfo.java)."""
    g = node.gossiper
    now = g.clock()
    out = []
    with g._lock:
        for ep, st in g.states.items():
            if ep == g.ep:
                continue
            out.append({"endpoint": ep.name, "alive": st.alive,
                        "phi": round(g.detector.phi(st, now), 3)})
    return out


def gcstats(node=None, engine=None) -> dict:
    """nodetool gcstats — the runtime's collector statistics (for a
    Python runtime: gc generation counts/collections, the JVM GC role)."""
    import gc
    stats = gc.get_stats()
    return {"collections": [s.get("collections", 0) for s in stats],
            "collected": [s.get("collected", 0) for s in stats],
            "uncollectable": [s.get("uncollectable", 0) for s in stats],
            "tracked_objects": len(gc.get_objects())}


def tablehistograms(engine, keyspace: str | None = None,
                    table: str | None = None) -> dict:
    """nodetool tablehistograms [<ks> [<table>]]: per-table
    distributions (tools/nodetool/TableHistograms.java) — reference
    parity: read/write latency and SSTables-per-read percentiles from
    the live decaying histograms, beside the size/cell/partition
    distributions from sstable metadata."""
    out = {}
    for cfs in engine.stores.values():
        t = cfs.table
        if keyspace and t.keyspace != keyspace:
            continue
        if table and t.name != table:
            continue
        live = cfs.live_sstables()
        sizes = sorted(s.data_size for s in live)
        cells = sorted(s.n_cells for s in live)
        parts = sorted(s.n_partitions for s in live)

        def pct(v, p):
            return v[min(len(v) - 1, int(len(v) * p))] if v else 0

        def latency(h):
            s = h.summary()   # one consistent read per hist
            return {"p50_us": s["p50_us"], "p95_us": s["p95_us"],
                    "p99_us": s["p99_us"], "max_us": s["max_us"],
                    "count": s["count"]}
        spr = cfs.sstables_per_read.summary()
        out[t.full_name()] = {
            "sstables": len(live),
            "data_size": {"p50": pct(sizes, 0.5), "max": pct(sizes, 1.0)},
            "cells": {"p50": pct(cells, 0.5), "max": pct(cells, 1.0)},
            "partitions": {"p50": pct(parts, 0.5),
                           "max": pct(parts, 1.0)},
            "read_latency": latency(cfs.read_hist),
            "write_latency": latency(cfs.write_hist),
            # the hist records sstables CONSULTED per point read, so
            # the "_us" summary keys are unit-less counts here
            "sstables_per_read": {"p50": spr["p50_us"],
                                  "p95": spr["p95_us"],
                                  "p99": spr["p99_us"],
                                  "max": spr["max_us"],
                                  "count": spr["count"]},
        }
    return out


def toppartitions(engine, keyspace: str, table: str,
                  k: int = 10) -> list[dict]:
    """nodetool toppartitions: largest partitions by on-disk cells,
    summed across live sstables' partition directories
    (tools/nodetool/TopPartitions.java, size sampler role)."""
    import numpy as np
    cfs = engine.store(keyspace, table)
    totals: dict[bytes, int] = {}
    for sst in cfs.live_sstables():
        # per-partition cell counts: first-cell offsets diffed against
        # the next start (the last partition runs to n_cells)
        c0 = np.append(np.asarray(sst._part_cell0), sst.n_cells)
        for i in range(sst.n_partitions):
            pk = sst.partition_key_at(i)
            totals[pk] = totals.get(pk, 0) + int(c0[i + 1] - c0[i])
    top = sorted(totals.items(), key=lambda kv: -kv[1])[:k]
    return [{"partition_key": pk.hex(), "cells": n} for pk, n in top]


def rangekeysample(engine, keyspace: str, table: str,
                   n: int = 100) -> list[str]:
    """nodetool rangekeysample: sampled partition keys from the
    partition directories (tools/nodetool/RangeKeySample.java)."""
    cfs = engine.store(keyspace, table)
    keys = []
    for sst in cfs.live_sstables():
        step = max(1, sst.n_partitions // max(1, n // max(
            1, len(cfs.live_sstables()))))
        for i in range(0, sst.n_partitions, step):
            keys.append(sst.partition_key_at(i).hex())
    return keys[:n]


def datapaths(engine, keyspace: str | None = None) -> dict:
    """nodetool datapaths (tools/nodetool/DataPaths.java)."""
    return {cfs.table.full_name(): cfs.directory
            for cfs in engine.stores.values()
            if not keyspace or cfs.table.keyspace == keyspace}


def viewbuildstatus(node, keyspace: str | None = None) -> list[dict]:
    """nodetool viewbuildstatus (tools/nodetool/ViewBuildStatus.java):
    registered views and their backfill state (registrations persist;
    backfill runs at CREATE, so a registered view is built)."""
    out = []
    for (ks, name), info in getattr(node.schema, "views", {}).items():
        if keyspace and ks != keyspace:
            continue
        out.append({"keyspace": ks, "view": name,
                    "base": ".".join(info.get("base", ("?", "?"))),
                    "status": "SUCCESS"})
    return out


# ---- gossip / binary / protocol toggles ----------------------------------


def disablegossip(node) -> dict:
    node.gossiper.stop()
    return {"gossip": "stopped"}


def enablegossip(node) -> dict:
    if not node.gossiper.is_running():
        node.gossiper.start()
    return {"gossip": "running"}


def disablebinary(node) -> dict:
    """Refuse NEW native-protocol connections (in-flight ones drain —
    tools/nodetool/DisableBinary.java semantics)."""
    for srv in getattr(node, "cql_servers", []):
        srv.paused = True
    return {"native_transport": "paused"}


def enablebinary(node) -> dict:
    for srv in getattr(node, "cql_servers", []):
        srv.paused = False
    return {"native_transport": "running"}


def disableoldprotocolversions(node) -> dict:
    """Only the NEWEST protocol version may connect
    (tools/nodetool/DisableOldProtocolVersions.java)."""
    out = {}
    for srv in getattr(node, "cql_servers", []):
        from ..transport.frame import SUPPORTED_VERSIONS
        srv.min_version = max(SUPPORTED_VERSIONS)
        out["min_version"] = srv.min_version
    return out or {"min_version": None}


def enableoldprotocolversions(node) -> dict:
    out = {}
    for srv in getattr(node, "cql_servers", []):
        from ..transport.frame import SUPPORTED_VERSIONS
        srv.min_version = min(SUPPORTED_VERSIONS)
        out["min_version"] = srv.min_version
    return out or {"min_version": None}


# ---- hints ---------------------------------------------------------------


def pausehandoff(node) -> dict:
    """Alias pair of disable/enablehandoff the reference also ships."""
    node.hints.enabled = False
    return {"handoff": "paused"}


def resumehandoff(node) -> dict:
    node.hints.enabled = True
    return {"handoff": "running"}


def disablehintsfordc(node, dc: str) -> dict:
    node.hints.disabled_dcs.add(dc)
    return {"hints_disabled_dcs": sorted(node.hints.disabled_dcs)}


def enablehintsfordc(node, dc: str) -> dict:
    node.hints.disabled_dcs.discard(dc)
    return {"hints_disabled_dcs": sorted(node.hints.disabled_dcs)}


def getmaxhintwindow(node) -> dict:
    return {"max_hint_window_ms": node.max_hint_window_ms}


def setmaxhintwindow(node, ms: int) -> dict:
    node.max_hint_window_ms = int(ms)
    return {"max_hint_window_ms": node.max_hint_window_ms}


# ---- seeds / schema / triggers / batchlog --------------------------------


def getseeds(node) -> list[str]:
    return [e.name for e in node.gossiper.seeds]


def reloadseeds(node, seeds: list | None = None) -> list[str]:
    """Re-resolve the seed list (tools/nodetool/ReloadSeeds.java);
    in-process deployments pass the new list directly."""
    if seeds:
        by_name = {e.name: e for e in node.ring.endpoints}
        node.gossiper.seeds = [by_name[s] for s in seeds if s in by_name]
    return getseeds(node)


def resetlocalschema(node) -> dict:
    """Drop to the cluster's schema log state and re-pull
    (tools/nodetool/ResetLocalSchema.java)."""
    sync = getattr(node, "schema_sync", None)
    if sync is None:
        return {"pulled": False, "reason": "no metadata log on this node"}
    ok = sync.pull_from_peers(timeout=5.0)
    return {"pulled": ok, "epoch": sync.epoch}


def reloadlocalschema(node) -> dict:
    """Reload schema from the local epoch log
    (tools/nodetool/ReloadLocalSchema.java)."""
    sync = getattr(node, "schema_sync", None)
    if sync is None:
        return {"epoch": None,
                "reason": "no metadata log on this node",
                "tables": sum(len(k.tables) for k in
                              node.schema.keyspaces.values())}
    return {"epoch": sync.epoch,
            "entries": len(sync.entries_after(0))}


def reloadtriggers(node) -> dict:
    """Re-load trigger code from the triggers directory
    (tools/nodetool/ReloadTriggers.java): drop the compiled-function
    cache so every registered trigger re-imports its file on next
    fire — updated trigger code takes effect without DDL."""
    trg = getattr(node.engine, "triggers", None)
    if trg is None:
        return {"triggers": "no trigger service"}
    n = len(trg._fns)
    trg._fns.clear()
    return {"triggers": "reloaded", "cached_fns_dropped": n}


def replaybatchlog(node) -> dict:
    """Force a batchlog replay pass (tools/nodetool/ReplayBatchlog.java)."""
    n = 0
    for bid, mutations in list(node.batchlog.pending()):
        for m in mutations:
            node.engine.apply(m)
        node.batchlog.remove(bid)
        n += 1
    return {"replayed_batches": n}


# ---- caches --------------------------------------------------------------


def invalidatekeycache(engine) -> dict:
    """The key cache is process-global (storage/key_cache.GLOBAL),
    generation-scoped per sstable — clear it wholesale."""
    from ..storage.key_cache import GLOBAL as key_cache
    n = len(key_cache.keys())
    key_cache.clear()
    return {"cleared": n}


def _invalidate_auth_cache(node) -> dict:
    auth = getattr(node.engine, "auth", None)
    if auth is None:
        return {"invalidated": False}
    auth.cache.invalidate_all()
    return {"invalidated": True}


def invalidatepermissionscache(node) -> dict:
    return _invalidate_auth_cache(node)


def invalidaterolescache(node) -> dict:
    return _invalidate_auth_cache(node)


def invalidatenetworkpermissionscache(node) -> dict:
    return _invalidate_auth_cache(node)


def invalidatecidrpermissionscache(node) -> dict:
    return _invalidate_auth_cache(node)


def setcachecapacity(engine, row_entries: int | None = None,
                     chunk_bytes: int | None = None) -> dict:
    """nodetool setcachecapacity (row-cache entries, chunk-cache bytes)."""
    out = {}
    if row_entries is not None:
        for cfs in engine.stores.values():
            if cfs.row_cache is not None:
                cfs.row_cache.capacity = int(row_entries)
        out["row_entries"] = int(row_entries)
    if chunk_bytes is not None:
        from ..storage import chunk_cache
        chunk_cache.GLOBAL.capacity = int(chunk_bytes)
        out["chunk_bytes"] = int(chunk_bytes)
    return out


# ---- auth / cidr ---------------------------------------------------------


def getauthcacheconfig(node) -> dict:
    auth = getattr(node.engine, "auth", None)
    return {"validity_seconds": auth.cache.validity if auth else None}


def setauthcacheconfig(node, validity_seconds: float) -> dict:
    auth = getattr(node.engine, "auth", None)
    if auth is None:
        raise RuntimeError("auth is not enabled")
    auth.cache.validity = float(validity_seconds)
    auth.cache.invalidate_all()
    return {"validity_seconds": auth.cache.validity}


def getcidrgroupsofip(node, ip: str) -> list[str]:
    """CIDR groups containing an address
    (tools/nodetool/GetCIDRGroupsOfIP.java)."""
    import ipaddress
    auth = getattr(node.engine, "auth", None)
    if auth is None:
        return []
    addr = ipaddress.ip_address(ip)
    return sorted(name for name, cidrs in auth.cidr_groups.items()
                  if any(addr in ipaddress.ip_network(c)
                         for c in cidrs))


def cidrfilteringstats(node) -> dict:
    auth = getattr(node.engine, "auth", None)
    if auth is None:
        return {"groups": 0, "cidrs": 0, "restricted_roles": 0}
    return {"groups": len(auth.cidr_groups),
            "cidrs": sum(len(v) for v in auth.cidr_groups.values()),
            "restricted_roles": sum(
                1 for r in auth.roles.values()
                if r.get("cidr_groups"))}


# ---- audit / FQL ---------------------------------------------------------


def enableauditlog(node, path: str | None = None) -> dict:
    import os as _os

    from ..service.audit import AuditLog
    if node.engine.audit_log is None:
        path = path or _os.path.join(node.engine.data_dir, "audit.jsonl")
        node.engine.audit_log = AuditLog(path)
    return {"audit": "enabled", "path": node.engine.audit_log.path}


def disableauditlog(node) -> dict:
    if node.engine.audit_log is not None:
        node.engine.audit_log.close()
        node.engine.audit_log = None
    return {"audit": "disabled"}


def getauditlog(node) -> dict:
    a = node.engine.audit_log
    return {"enabled": a is not None,
            "path": a.path if a is not None else None}


def enablefullquerylog(node, path: str | None = None) -> dict:
    import os as _os

    from ..service.audit import AuditLog
    if node.engine.fql_log is None:
        path = path or _os.path.join(node.engine.data_dir, "fql.jsonl")
        node.engine.fql_log = AuditLog(path)
    return {"fql": "enabled", "path": node.engine.fql_log.path}


def disablefullquerylog(node) -> dict:
    if node.engine.fql_log is not None:
        node.engine.fql_log.close()
        node.engine.fql_log = None
    return {"fql": "disabled"}


def getfullquerylog(node) -> dict:
    f = node.engine.fql_log
    return {"enabled": f is not None,
            "path": f.path if f is not None else None}


def resetfullquerylog(node) -> dict:
    """Disable AND delete the log file
    (tools/nodetool/ResetFullQueryLog.java)."""
    import os as _os
    f = node.engine.fql_log
    path = f.path if f is not None else None
    disablefullquerylog(node)
    if path and _os.path.exists(path):
        _os.remove(path)
    return {"fql": "reset"}


# ---- compaction / sstables ----------------------------------------------


def getcompactionthreshold(engine, keyspace: str, table: str) -> dict:
    cfs = engine.store(keyspace, table)
    opts = cfs.table.params.compaction
    return {"min_threshold": int(opts.get("min_threshold", 4)),
            "max_threshold": int(opts.get("max_threshold", 32))}


def setcompactionthreshold(engine, keyspace: str, table: str,
                           min_threshold: int,
                           max_threshold: int) -> dict:
    if int(min_threshold) < 2 or int(max_threshold) < int(min_threshold):
        raise ValueError("need 2 <= min_threshold <= max_threshold")
    cfs = engine.store(keyspace, table)
    cfs.table.params.compaction["min_threshold"] = int(min_threshold)
    cfs.table.params.compaction["max_threshold"] = int(max_threshold)
    return getcompactionthreshold(engine, keyspace, table)


def stop(engine, compaction_type: str | None = None) -> dict:
    """nodetool stop: abort in-flight compactions cooperatively — the
    stop request lands on each active task's OWN progress handle, so it
    covers exactly the tasks running NOW (a task starting a moment
    later is unaffected — the reference's semantics) and a task that
    has not polled yet still sees it; every signalled task rolls back
    through its lifecycle transaction (tools/nodetool/Stop.java,
    CompactionInfo.Holder.stop). The shared cfs.compaction_abort event
    remains a programmatic kill switch for tasks driven outside the
    manager; it is deliberately NOT pulsed here — a timed pulse would
    spuriously abort tasks that start inside the window."""
    n = engine.compactions.stop_active()
    return {"stopped": True, "signalled": n}


def stopdaemon(node) -> dict:
    """nodetool stopdaemon: full node shutdown
    (tools/nodetool/StopDaemon.java). In a daemon the process exits via
    its signal handler; in-process callers get a stopped node."""
    node.shutdown()
    return {"daemon": "stopped"}


def forcecompact(engine, keyspace: str, table: str) -> dict:
    """nodetool forcecompact (major on one table, ignoring strategy
    selection — tools/nodetool/ForceCompact.java)."""
    out = engine.compactions.major_compaction(engine.store(keyspace,
                                                           table))
    return out or {"compacted": False}


def recompresssstables(engine, keyspace: str,
                       table: str | None = None) -> list[dict]:
    """nodetool recompress_sstables: rewrite under the CURRENT
    compression params (tools/nodetool/RecompressSSTables.java) — the
    upgradesstables machinery with a forced rewrite."""
    return upgradesstables(engine, keyspace, table)


def rebuildindex(node, keyspace: str, table: str,
                 index_names: str | None = None) -> dict:
    """nodetool rebuild_index: drop the index's per-sstable components
    and rebuild from base data (tools/nodetool/RebuildIndex.java)."""
    registry = getattr(node, "indexes", None) or         getattr(node.engine, "indexes", None)
    if registry is None:
        raise RuntimeError("no index registry")
    rebuilt = []
    for (ks0, tb0, col), idx in list(registry.indexes.items()):
        if ks0 != keyspace or tb0 != table:
            continue
        if hasattr(idx, "rebuild"):
            idx.rebuild()
        rebuilt.append(col)
    return {"rebuilt": rebuilt}


# ---- backups -------------------------------------------------------------


def enablebackup(engine) -> dict:
    engine.incremental_backup = True
    return {"incremental_backup": True}


def disablebackup(engine) -> dict:
    engine.incremental_backup = False
    return {"incremental_backup": False}


def statusbackup(engine) -> dict:
    return {"incremental_backup": bool(engine.incremental_backup)}



def import_sstables(engine, keyspace: str, table: str,
                    directory: str) -> dict:
    """nodetool import (tools/nodetool/Import.java): copy sstables from
    an external directory into the table's data directory under fresh
    generations, then load them — the safer successor to `refresh`
    (files never collide with live generations)."""
    import os as _os
    import shutil as _shutil

    from ..storage.sstable import Descriptor
    cfs = engine.store(keyspace, table)
    descs = Descriptor.list_in(directory)
    if not descs:
        raise FileNotFoundError(f"no sstables under {directory}")
    copied = 0
    for desc in descs:
        gen = cfs.next_generation()
        prefix = f"{desc.version}-{desc.generation}-"
        for fn in sorted(_os.listdir(directory)):
            if fn.startswith(prefix):
                _shutil.copy2(
                    _os.path.join(directory, fn),
                    _os.path.join(cfs.directory,
                                  f"{desc.version}-{gen}-{fn[len(prefix):]}"))
        copied += 1
    cfs.reload_sstables()
    return {"imported_sstables": copied,
            "live_sstables": len(cfs.live_sstables())}


for _name, _target in [
        ("status", "node"), ("info", "engine"), ("ring", "node"),
        ("flush", "engine"), ("compact", "engine"),
        ("compactionstats", "engine"), ("commitlogstats", "engine"),
        ("tablestats", "engine"),
        ("repair", "node"), ("cleanup", "node"),
        ("getendpoints", "node"), ("gossipinfo", "node"),
        ("version", "none"), ("describecluster", "node"),
        ("setcompactionthroughput", "engine"),
        ("getcompactionthroughput", "engine"),
        ("setslowquerythreshold", "engine"),
        ("upgradesstables", "engine"), ("sstablesplit", "engine"),
        ("snapshot", "engine"), ("listsnapshots", "engine"),
        ("clearsnapshot", "engine"), ("scrub", "engine"),
        ("garbagecollect", "engine"),
        ("netstats", "node"), ("tpstats", "engine"),
        ("proxyhistograms", "node"), ("compactionhistory", "engine"),
        ("clientstats", "node"), ("gettimeout", "node"),
        ("settimeout", "node"), ("getstreamthroughput", "engine"),
        ("setstreamthroughput", "engine"),
        ("getconcurrentcompactors", "engine"),
        ("setconcurrentcompactors", "engine"),
        ("gettraceprobability", "engine"),
        ("settraceprobability", "engine"),
        ("gettraces", "engine"), ("exportmetrics", "engine"),
        ("diagnostics", "engine"), ("flightrecorder", "engine"),
        ("pipelinestats", "engine"), ("slostats", "engine"),
        ("metricshistory", "engine"), ("profiler", "engine"),
        ("clusterstats", "node"),
        ("disableautocompaction", "engine"),
        ("enableautocompaction", "engine"),
        ("statusautocompaction", "engine"),
        ("autocompaction", "engine"),
        ("disablehandoff", "node"), ("enablehandoff", "node"),
        ("statushandoff", "node"), ("truncatehints", "node"),
        ("statusgossip", "node"), ("statusbinary", "node"),
        ("drain", "node"), ("refresh", "engine"),
        ("invalidaterowcache", "engine"),
        ("invalidatechunkcache", "engine"),
        ("invalidatecountercache", "node"),
        ("getsstables", "engine"), ("verify", "engine"),
        ("listquarantine", "engine"),
        ("assassinate", "node"), ("listpendinghints", "node"),
        ("getlogginglevels", "none"), ("setlogginglevel", "none"),
        ("updatecidrgroup", "engine"), ("dropcidrgroup", "engine"),
        ("listcidrgroups", "engine"),
        ("invalidatecredentialscache", "engine"),
        ("decommission", "node"), ("move", "node"),
        ("bulkload", "node"), ("rebuild", "node"),
        ("repair_admin", "node"),
        ("describering", "node"), ("cmsadmin", "node"),
        ("failuredetectorinfo", "node"), ("gcstats", "none"),
        ("tablehistograms", "engine"),
        ("toppartitions", "engine"), ("rangekeysample", "engine"),
        ("datapaths", "engine"), ("viewbuildstatus", "node"),
        ("disablegossip", "node"), ("enablegossip", "node"),
        ("disablebinary", "node"), ("enablebinary", "node"),
        ("disableoldprotocolversions", "node"),
        ("enableoldprotocolversions", "node"),
        ("pausehandoff", "node"), ("resumehandoff", "node"),
        ("disablehintsfordc", "node"), ("enablehintsfordc", "node"),
        ("getmaxhintwindow", "node"), ("setmaxhintwindow", "node"),
        ("getseeds", "node"), ("reloadseeds", "node"),
        ("resetlocalschema", "node"), ("reloadlocalschema", "node"),
        ("reloadtriggers", "node"), ("replaybatchlog", "node"),
        ("invalidatekeycache", "engine"),
        ("invalidatepermissionscache", "node"),
        ("invalidaterolescache", "node"),
        ("invalidatenetworkpermissionscache", "node"),
        ("invalidatecidrpermissionscache", "node"),
        ("setcachecapacity", "engine"),
        ("getauthcacheconfig", "node"), ("setauthcacheconfig", "node"),
        ("getcidrgroupsofip", "node"), ("cidrfilteringstats", "node"),
        ("enableauditlog", "node"), ("disableauditlog", "node"),
        ("getauditlog", "node"),
        ("enablefullquerylog", "node"), ("disablefullquerylog", "node"),
        ("getfullquerylog", "node"), ("resetfullquerylog", "node"),
        ("getcompactionthreshold", "engine"),
        ("setcompactionthreshold", "engine"),
        ("stop", "engine"), ("stopdaemon", "node"),
        ("forcecompact", "engine"), ("recompresssstables", "engine"),
        ("rebuildindex", "node"),
        ("enablebackup", "engine"), ("disablebackup", "engine"),
        ("statusbackup", "engine")]:
    COMMANDS[_name] = (_target, globals()[_name])
# reserved word: the function is import_sstables, the command 'import'
COMMANDS["import"] = ("engine", import_sstables)


def run_command(name: str, node=None, engine=None, **kwargs):
    """Dispatch one command against whatever backend is available —
    shared by the CLI local mode and the admin server."""
    if name not in COMMANDS:
        raise ValueError(f"unknown command {name!r}")
    target, fn = COMMANDS[name]
    if target == "node":
        if node is None:
            raise ValueError(f"{name} needs a running node "
                             "(use --host/--port admin mode)")
        return fn(node, **kwargs)
    if target == "engine":
        eng = engine if engine is not None \
            else (node.engine if node is not None else None)
        if eng is None:
            raise ValueError(f"{name} needs an engine")
        return fn(eng, **kwargs)
    return fn(**kwargs)


def main(argv=None):
    p = argparse.ArgumentParser(
        prog="nodetool",
        description="Operator commands. --host/--port drives a running "
                    "daemon over the admin protocol (JMX role); --data "
                    "opens a local data directory offline.")
    p.add_argument("command", choices=sorted(COMMANDS))
    p.add_argument("args", nargs="*", help="key=value command arguments")
    p.add_argument("--data", help="offline mode: data directory")
    p.add_argument("--host", help="admin mode: daemon host")
    p.add_argument("--port", type=int, help="admin mode: admin port")
    p.add_argument("--secret", help="admin mode: shared secret "
                   "(or env CTPU_ADMIN_SECRET)")
    args = p.parse_args(argv)

    kwargs = {}
    for kv in args.args:
        if "=" not in kv:
            p.error(f"arguments are key=value, got {kv!r}")
        k, v = kv.split("=", 1)
        try:
            kwargs[k] = json.loads(v)
        except json.JSONDecodeError:
            kwargs[k] = v

    if args.host and args.port:
        import os as _os

        from ..service.admin import admin_call
        out = admin_call(args.host, args.port, args.command, kwargs,
                         secret=args.secret
                         or _os.environ.get("CTPU_ADMIN_SECRET"))
        print(json.dumps(out, indent=2, default=str))
        return
    if not args.data:
        p.error("need --data DIR (offline) or --host/--port (admin mode)")
    from ..schema import Schema
    from ..storage.engine import StorageEngine
    engine = StorageEngine(args.data, Schema())
    try:
        print(json.dumps(run_command(args.command, engine=engine,
                                     **kwargs),
                         indent=2, default=str))
    finally:
        engine.close()


if __name__ == "__main__":
    main()
