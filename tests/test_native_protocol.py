"""Native-protocol server + client driver over real sockets
(transport/Server.java + Dispatcher.java roles, protocol v4 subset)."""
import pytest

from cassandra_tpu.client import Cluster, DriverError, serialize_params
from cassandra_tpu.schema import Schema
from cassandra_tpu.storage.engine import StorageEngine
from cassandra_tpu.transport_server import CQLServer


@pytest.fixture
def server(tmp_path):
    eng = StorageEngine(str(tmp_path / "data"), Schema(),
                        commitlog_sync="batch")
    srv = CQLServer(eng)
    yield eng, srv
    srv.close()
    eng.close()


def test_wire_query_roundtrip(server):
    eng, srv = server
    s = Cluster("127.0.0.1", srv.port).connect()
    s.execute("CREATE KEYSPACE ks WITH replication = "
              "{'class': 'SimpleStrategy', 'replication_factor': 1}")
    s.execute("USE ks")
    s.execute("CREATE TABLE kv (k int PRIMARY KEY, v text, n bigint)")
    s.execute("INSERT INTO kv (k, v, n) VALUES (1, 'hello', 42)")
    rows = s.execute("SELECT k, v, n FROM kv WHERE k = 1")
    assert rows.column_names == ["k", "v", "n"]
    assert rows.rows == [(1, "hello", 42)]
    s.close()


def test_wire_bound_values(server):
    eng, srv = server
    s = Cluster("127.0.0.1", srv.port).connect()
    s.execute("CREATE KEYSPACE ks WITH replication = "
              "{'class': 'SimpleStrategy', 'replication_factor': 1}")
    s.execute("USE ks")
    s.execute("CREATE TABLE b (k int PRIMARY KEY, v text)")
    t = eng.schema.get_table("ks", "b")
    params = serialize_params(t, ["k", "v"], [7, "bound"])
    s.execute("INSERT INTO b (k, v) VALUES (?, ?)", params)
    rows = s.execute("SELECT v FROM b WHERE k = ?",
                     serialize_params(t, ["k"], [7]))
    assert rows.rows == [("bound",)]
    s.close()


def test_wire_paging(server):
    eng, srv = server
    s = Cluster("127.0.0.1", srv.port).connect()
    s.execute("CREATE KEYSPACE ks WITH replication = "
              "{'class': 'SimpleStrategy', 'replication_factor': 1}")
    s.execute("USE ks")
    s.execute("CREATE TABLE p (k int PRIMARY KEY, v int)")
    for i in range(40):
        s.execute(f"INSERT INTO p (k, v) VALUES ({i}, {i})")
    got, state, pages = [], None, 0
    while True:
        rows = s.execute("SELECT k FROM p", fetch_size=12,
                         paging_state=state)
        got.extend(r[0] for r in rows.rows)
        pages += 1
        state = rows.paging_state
        if state is None:
            break
    assert sorted(got) == list(range(40))
    assert pages >= 4
    s.close()


def test_wire_errors(server):
    eng, srv = server
    s = Cluster("127.0.0.1", srv.port).connect()
    with pytest.raises(DriverError, match="0x2200"):
        s.execute("SELECT * FROM nosuch.table")
    s.close()


def test_wire_auth(tmp_path):
    eng = StorageEngine(str(tmp_path / "data"), Schema(),
                        commitlog_sync="batch", auth_enabled=True)
    srv = CQLServer(eng)
    try:
        with pytest.raises(DriverError):
            Cluster("127.0.0.1", srv.port, "cassandra", "wrong").connect()
        s = Cluster("127.0.0.1", srv.port, "cassandra",
                    "cassandra").connect()
        s.execute("CREATE KEYSPACE ks WITH replication = "
                  "{'class': 'SimpleStrategy', 'replication_factor': 1}")
        s.close()
    finally:
        srv.close()
        eng.close()


@pytest.mark.slow
def test_wire_client_against_noded_daemon(tmp_path):
    """Full stack over processes and sockets: noded daemon serving the
    native protocol; a client connects to its port and runs CQL."""
    import json
    import os
    import subprocess
    import sys

    from cassandra_tpu.cluster.ring import even_tokens
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    cfg = {
        "name": "solo", "host": "127.0.0.1", "port": 0,
        "tokens": even_tokens(1, vnodes=4)[0],
        "data_dir": str(tmp_path / "solo"),
        "peers": [], "seeds": [], "jax_platform": "cpu",
        "native_port": 0,
        "ddl": ["CREATE KEYSPACE ks WITH replication = "
                "{'class': 'SimpleStrategy', 'replication_factor': 1}",
                "CREATE TABLE ks.kv (k int PRIMARY KEY, v text)"],
    }
    cfile = tmp_path / "solo.json"
    cfile.write_text(json.dumps(cfg))
    p = subprocess.Popen(
        [sys.executable, "-m", "cassandra_tpu.tools.noded", str(cfile)],
        cwd=repo, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
        text=True)
    try:
        line = p.stdout.readline()
        assert line.startswith("READY"), (line, p.stderr.read())
        native_port = int(line.split("NATIVE")[1].strip())
        s = Cluster("127.0.0.1", native_port).connect()
        s.execute("USE ks")
        s.execute("INSERT INTO kv (k, v) VALUES (5, 'from-the-wire')")
        assert s.execute("SELECT v FROM kv WHERE k = 5").rows \
            == [("from-the-wire",)]
        s.close()
    finally:
        p.terminate()
        try:
            p.wait(timeout=10)
        except subprocess.TimeoutExpired:
            p.kill()


def test_wire_prepare_execute(server):
    eng, srv = server
    s = Cluster("127.0.0.1", srv.port).connect()
    s.execute("CREATE KEYSPACE ks WITH replication = "
              "{'class': 'SimpleStrategy', 'replication_factor': 1}")
    s.execute("USE ks")
    s.execute("CREATE TABLE pr (k int PRIMARY KEY, v text)")
    t = eng.schema.get_table("ks", "pr")
    qid = s.prepare("INSERT INTO pr (k, v) VALUES (?, ?)")
    for i in range(5):
        s.execute_prepared(qid, serialize_params(t, ["k", "v"],
                                                 [i, f"v{i}"]))
    sel = s.prepare("SELECT v FROM pr WHERE k = ?")
    rows = s.execute_prepared(sel, serialize_params(t, ["k"], [3]))
    assert rows.rows == [("v3",)]
    s.close()


def test_wire_v4_still_supported(server):
    eng, srv = server
    s = Cluster("127.0.0.1", srv.port, protocol_version=4).connect()
    s.execute("CREATE KEYSPACE v4ks WITH replication = "
              "{'class': 'SimpleStrategy', 'replication_factor': 1}")
    s.execute("USE v4ks")
    s.execute("CREATE TABLE kv (k int PRIMARY KEY, v text)")
    s.execute("INSERT INTO kv (k, v) VALUES (1, 'legacy')")
    assert s.execute("SELECT v FROM kv WHERE k = 1").rows == [("legacy",)]
    s.close()


def test_wire_unsupported_version_rejected(server):
    import socket
    import struct
    _eng, srv = server
    sock = socket.create_connection(("127.0.0.1", srv.port), timeout=5.0)
    # protocol v3 STARTUP: server must answer a PROTOCOL error, not
    # misparse the stream
    body = struct.pack(">H", 1) + b"\x00\x0bCQL_VERSION\x00\x053.4.5"
    sock.sendall(struct.pack(">BBhBI", 0x03, 0, 0, 0x01, len(body)) + body)
    hdr = sock.recv(9)
    opcode = hdr[4]
    (length,) = struct.unpack(">I", hdr[5:9])
    rbody = sock.recv(length)
    (code,) = struct.unpack_from(">i", rbody, 0)
    assert opcode == 0x00 and code == 0x000A   # ERROR / PROTOCOL
    sock.close()


def test_wire_compression_flag_rejected(server):
    import socket
    import struct
    _eng, srv = server
    sock = socket.create_connection(("127.0.0.1", srv.port), timeout=5.0)
    body = struct.pack(">H", 1) + b"\x00\x0bCQL_VERSION\x00\x053.4.5"
    # flags=0x01 claims lz4 compression that was never negotiated
    sock.sendall(struct.pack(">BBhBI", 0x04, 0x01, 0, 0x01, len(body))
                 + body)
    hdr = sock.recv(9)
    (length,) = struct.unpack(">I", hdr[5:9])
    rbody = sock.recv(length)
    (code,) = struct.unpack_from(">i", rbody, 0)
    assert hdr[4] == 0x00 and code == 0x000A
    sock.close()


def test_v5_segment_crc_utilities():
    from cassandra_tpu import transport_server as ts
    payload = b"hello v5 framing" * 100
    seg = ts.encode_segment(payload)
    plen, sc = ts.decode_segment_header(seg[:6])
    assert plen == len(payload) and sc
    # corrupt the header -> CRC24 failure
    import pytest as _pytest
    bad = bytearray(seg[:6])
    bad[0] ^= 0xFF
    with _pytest.raises(ValueError):
        ts.decode_segment_header(bytes(bad))


def test_v5_prepared_roundtrip(server):
    eng, srv = server
    s = Cluster("127.0.0.1", srv.port, protocol_version=5).connect()
    s.execute("CREATE KEYSPACE pks WITH replication = "
              "{'class': 'SimpleStrategy', 'replication_factor': 1}")
    s.execute("USE pks")
    s.execute("CREATE TABLE kv (k int PRIMARY KEY, v text)")
    t = eng.schema.get_table("pks", "kv")
    qid = s.prepare("INSERT INTO kv (k, v) VALUES (?, ?)")
    for i in range(5):
        s.execute_prepared(qid, serialize_params(t, ["k", "v"],
                                                 [i, f"p{i}"]))
    rid = s.prepare("SELECT v FROM kv WHERE k = ?")
    rows = s.execute_prepared(rid, serialize_params(t, ["k"], [3]))
    assert rows.rows == [("p3",)]
    s.close()


def test_events_status_topology_schema(tmp_path):
    """A registered driver observes a node death, a topology change and
    DDL performed by ANOTHER session (RegisterMessage/EventMessage +
    Server push; VERDICT round-2 item 7's done-criterion)."""
    import time
    from cassandra_tpu.cluster.node import LocalCluster

    cluster = LocalCluster(2, str(tmp_path), rf=1,
                           gossip_interval=0.05)
    srv = CQLServer(cluster.node(1))
    try:
        s = Cluster("127.0.0.1", srv.port).connect()
        s.register(["STATUS_CHANGE", "TOPOLOGY_CHANGE", "SCHEMA_CHANGE"])

        # schema change from a DIFFERENT session (direct node session)
        other = cluster.session(1)
        other.execute("CREATE KEYSPACE evks WITH replication = "
                      "{'class': 'SimpleStrategy', "
                      "'replication_factor': 2}")
        ev = s.wait_event(10.0)
        assert ev and ev["type"] == "SCHEMA_CHANGE" \
            and ev["change"] == "CREATED" and ev["keyspace"] == "evks"

        # node death: stop node2, wait for conviction -> STATUS DOWN
        cluster.stop_node(2)
        deadline = time.time() + 30
        ev = None
        while time.time() < deadline:
            ev = s.wait_event(2.0)
            if ev and ev["type"] == "STATUS_CHANGE" \
                    and ev["change"] == "DOWN":
                break
        assert ev and ev["type"] == "STATUS_CHANGE" \
            and ev["change"] == "DOWN"

        # topology change: replace the dead node -> NEW_NODE event
        cluster.replace_dead_node(2)
        deadline = time.time() + 10
        saw_new = False
        while time.time() < deadline and not saw_new:
            ev = s.wait_event(2.0)
            if ev and ev["type"] == "TOPOLOGY_CHANGE" \
                    and ev["change"] == "NEW_NODE":
                saw_new = True
        assert saw_new
        s.close()
    finally:
        srv.close()
        cluster.shutdown()
