"""Schema metadata: keyspaces, tables, columns.

Reference: schema/TableMetadata.java, KeyspaceMetadata.java, TableParams
(compaction/compression per-table options — the TPU backend's opt-in seam,
SURVEY.md section 5.6), schema/Schema.java:66 (global registry).
"""
from __future__ import annotations

import threading
import uuid as uuid_mod
from dataclasses import dataclass, field

from .ops.codec import CompressionParams
from .types import CQLType, parse_type

# column-lane sentinels (storage/cellbatch.py sort order within a clustering)
COL_PARTITION_DEL = 0   # partition-level deletion record
COL_ROW_DEL = 1         # row-level deletion record
COL_ROW_LIVENESS = 2    # primary-key liveness (row exists even if all null)
COL_RANGE_TOMB = 3      # range tombstone slice (storage/rangetomb.py)
COL_REGULAR_BASE = 8    # first real column id


class ColumnKind:
    PARTITION_KEY = "partition_key"
    CLUSTERING = "clustering"
    REGULAR = "regular"
    STATIC = "static"


@dataclass
class ColumnMetadata:
    name: str
    cql_type: CQLType
    kind: str
    position: int          # within its kind
    column_id: int = -1    # dense id >= COL_REGULAR_BASE for regular/static
    reversed: bool = False  # DESC clustering order


@dataclass
class TableParams:
    """Per-table options (reference schema/TableParams.java)."""
    compression: CompressionParams = field(default_factory=CompressionParams)
    compaction: dict = field(default_factory=lambda: {
        "class": "SizeTieredCompactionStrategy"})
    gc_grace_seconds: int = 864000  # 10 days, reference default
    default_ttl: int = 0
    memtable_flush_period_ms: int = 0
    comment: str = ""
    cdc: bool = False       # change data capture stream (storage/cdc.py)
    # row cache (cache/RowCache role): 'NONE' | 'ALL' rows per partition
    caching: dict = field(default_factory=lambda: {
        "keys": "ALL", "rows_per_partition": "NONE"})
    # TPU-format knob: bytes of clustering prefix carried in key lanes
    clustering_prefix_bytes: int = 16
    # at-rest encryption (TDE): sstable components encrypted under the
    # node's EncryptionContext keystore (storage/encryption.py)
    encryption: bool = False


class TableMetadata:
    def __init__(self, keyspace: str, name: str,
                 partition_key: list[tuple[str, CQLType]],
                 clustering: list[tuple[str, CQLType, bool]],
                 regular: list[tuple[str, CQLType]],
                 static: list[tuple[str, CQLType]] | None = None,
                 params: TableParams | None = None,
                 table_id: uuid_mod.UUID | None = None):
        self.keyspace = keyspace
        self.name = name
        self.id = table_id or uuid_mod.uuid4()
        self.params = params or TableParams()
        self.partition_key_columns: list[ColumnMetadata] = []
        self.clustering_columns: list[ColumnMetadata] = []
        self.regular_columns: list[ColumnMetadata] = []
        self.static_columns: list[ColumnMetadata] = []
        self.columns: dict[str, ColumnMetadata] = {}

        for i, (n, t) in enumerate(partition_key):
            self._add(ColumnMetadata(n, t, ColumnKind.PARTITION_KEY, i),
                      self.partition_key_columns)
        for i, (n, t, rev) in enumerate(clustering):
            self._add(ColumnMetadata(n, t, ColumnKind.CLUSTERING, i, reversed=rev),
                      self.clustering_columns)
        next_id = COL_REGULAR_BASE
        for i, (n, t) in enumerate(sorted(static or [])):
            c = ColumnMetadata(n, t, ColumnKind.STATIC, i, column_id=next_id)
            next_id += 1
            self._add(c, self.static_columns)
        for i, (n, t) in enumerate(sorted(regular)):
            c = ColumnMetadata(n, t, ColumnKind.REGULAR, i, column_id=next_id)
            next_id += 1
            self._add(c, self.regular_columns)
        self.columns_by_id = {c.column_id: c
                              for c in self.static_columns + self.regular_columns}

    def _add(self, col: ColumnMetadata, bucket: list[ColumnMetadata]):
        if col.name in self.columns:
            raise ValueError(f"duplicate column {col.name}")
        self.columns[col.name] = col
        bucket.append(col)

    # ------------------------------------------------------------ helpers --

    @property
    def clustering_lanes(self) -> int:
        return self.params.clustering_prefix_bytes // 4

    @property
    def is_counter_table(self) -> bool:
        return any(c.cql_type.is_counter for c in self.regular_columns)

    def primary_key_names(self) -> list[str]:
        return ([c.name for c in self.partition_key_columns]
                + [c.name for c in self.clustering_columns])

    def serialize_partition_key(self, values: list) -> bytes:
        """Single pk column: raw serialized bytes; composite: length-framed
        concatenation (reference CompositeType semantics)."""
        cols = self.partition_key_columns
        if len(cols) == 1:
            return cols[0].cql_type.serialize(values[0])
        out = bytearray()
        for c, v in zip(cols, values):
            b = c.cql_type.serialize(v)
            out += len(b).to_bytes(2, "big") + b + b"\x00"
        return bytes(out)

    def split_partition_key(self, key: bytes) -> list:
        cols = self.partition_key_columns
        if len(cols) == 1:
            return [cols[0].cql_type.deserialize(key)]
        out = []
        pos = 0
        for c in cols:
            ln = int.from_bytes(key[pos:pos + 2], "big")
            out.append(c.cql_type.deserialize(key[pos + 2:pos + 2 + ln]))
            pos += 2 + ln + 1
        return out

    def serialize_clustering(self, values: list) -> bytes:
        """Clustering tuple as a vint-length-framed concatenation of the
        serialized values — the form stored in cell payloads (invertible,
        unlike the byte-comparable form)."""
        from .utils import varint as vi
        out = bytearray()
        for c, v in zip(self.clustering_columns, values):
            b = c.cql_type.serialize(v)
            vi.write_unsigned_vint(len(b), out)
            out += b
        return bytes(out)

    def split_clustering(self, frame: bytes) -> list[bytes]:
        """Serialized clustering values from a payload frame (may be a
        prefix of the full clustering)."""
        from .utils import varint as vi
        vals = []
        pos = 0
        for _ in self.clustering_columns:
            if pos >= len(frame):
                break
            n, pos = vi.read_unsigned_vint(frame, pos)
            vals.append(bytes(frame[pos:pos + n]))
            pos += n
        return vals

    def deserialize_clustering(self, frame: bytes) -> list:
        return [c.cql_type.deserialize(b) for c, b in
                zip(self.clustering_columns, self.split_clustering(frame))]

    def clustering_comp(self, frame: bytes) -> bytes:
        """Byte-comparable composite for a serialized clustering frame."""
        from .utils import bytecomp
        comps = []
        desc = []
        for c, b in zip(self.clustering_columns, self.split_clustering(frame)):
            comps.append(c.cql_type.to_bytecomp(b))
            desc.append(c.reversed)
        return bytecomp.encode_composite(comps, desc)

    def clustering_bytecomp(self, values: list) -> bytes:
        """Byte-comparable composite of clustering values (full precision)."""
        from .utils import bytecomp
        comps = []
        desc = []
        for c, v in zip(self.clustering_columns, values):
            comps.append(c.cql_type.to_bytecomp(c.cql_type.serialize(v)))
            desc.append(c.reversed)
        return bytecomp.encode_composite(comps, desc)

    def full_name(self) -> str:
        return f"{self.keyspace}.{self.name}"

    def __repr__(self):
        return f"TableMetadata({self.full_name()})"


@dataclass
class KeyspaceParams:
    replication: dict = field(default_factory=lambda: {
        "class": "SimpleStrategy", "replication_factor": 1})
    durable_writes: bool = True


class KeyspaceMetadata:
    def __init__(self, name: str, params: KeyspaceParams | None = None):
        self.name = name
        self.params = params or KeyspaceParams()
        self.tables: dict[str, TableMetadata] = {}
        self.user_types: dict[str, CQLType] = {}

    def add_table(self, t: TableMetadata):
        if t.name in self.tables:
            raise ValueError(f"table {t.name} already exists")
        self.tables[t.name] = t


class Schema:
    """Process-global schema registry (reference schema/Schema.java:66).
    Distributed schema agreement arrives with the cluster-metadata layer."""

    def __init__(self):
        self.keyspaces: dict[str, KeyspaceMetadata] = {}
        self._by_id: dict = {}
        self._lock = threading.RLock()
        self.version = 0
        self.listeners: list = []  # persistence hooks (one per engine)
        # (keyspace, view_name) -> {"base": (ks, table)}; the view's own
        # TableMetadata lives in ks.tables like any table
        # (schema/ViewMetadata role)
        self.views: dict[tuple, dict] = {}

    def table_by_id(self, table_id) -> "TableMetadata | None":
        return self._by_id.get(table_id)

    def _changed(self):
        self.version += 1
        for fn in self.listeners:
            try:
                fn(self)
            except Exception as e:
                # a failed persistence write must not be silent: DDL
                # durability is at stake
                import sys
                print(f"schema listener failed: {e!r}", file=sys.stderr)

    def create_keyspace(self, name: str, params: KeyspaceParams | None = None,
                        if_not_exists: bool = False) -> KeyspaceMetadata:
        with self._lock:
            if name in self.keyspaces:
                if if_not_exists:
                    return self.keyspaces[name]
                raise ValueError(f"keyspace {name} already exists")
            ks = KeyspaceMetadata(name, params)
            self.keyspaces[name] = ks
            self._changed()
            return ks

    def drop_keyspace(self, name: str):
        with self._lock:
            ks = self.keyspaces.pop(name)
            for t in ks.tables.values():
                self._by_id.pop(t.id, None)
            self._changed()

    def add_table(self, t: TableMetadata):
        with self._lock:
            self.keyspaces[t.keyspace].add_table(t)
            self._by_id[t.id] = t
            self._changed()

    def drop_table(self, keyspace: str, name: str):
        with self._lock:
            t = self.keyspaces[keyspace].tables.pop(name)
            self._by_id.pop(t.id, None)
            self._changed()

    def get_table(self, keyspace: str, name: str) -> TableMetadata:
        ks = self.keyspaces.get(keyspace)
        if ks is None or name not in ks.tables:
            raise KeyError(f"unknown table {keyspace}.{name}")
        return ks.tables[name]


# ------------------------------------------------------------- persistence --

def table_to_dict(t: TableMetadata) -> dict:
    return {
        "keyspace": t.keyspace, "name": t.name, "id": str(t.id),
        "partition_key": [(c.name, repr(c.cql_type))
                          for c in t.partition_key_columns],
        "clustering": [(c.name, repr(c.cql_type), c.reversed)
                       for c in t.clustering_columns],
        "regular": [(c.name, repr(c.cql_type)) for c in t.regular_columns],
        "static": [(c.name, repr(c.cql_type)) for c in t.static_columns],
        # explicit ids: ALTERed tables must not re-derive ids from sorted
        # name order on reload (cells on disk reference these ids)
        "column_ids": {c.name: c.column_id
                       for c in t.static_columns + t.regular_columns},
        "params": {
            "compression": t.params.compression.to_dict(),
            "compaction": t.params.compaction,
            "gc_grace_seconds": t.params.gc_grace_seconds,
            "default_ttl": t.params.default_ttl,
            "comment": t.params.comment,
            "clustering_prefix_bytes": t.params.clustering_prefix_bytes,
            "cdc": t.params.cdc,
            "caching": t.params.caching,
            "encryption": t.params.encryption,
        },
    }


def table_from_dict(d: dict, udts: dict | None = None) -> TableMetadata:
    p = d["params"]
    params = TableParams(
        compression=CompressionParams.from_dict(p["compression"]),
        compaction=dict(p["compaction"]),
        gc_grace_seconds=int(p["gc_grace_seconds"]),
        default_ttl=int(p["default_ttl"]),
        comment=p.get("comment", ""),
        clustering_prefix_bytes=int(p.get("clustering_prefix_bytes", 16)),
        cdc=bool(p.get("cdc", False)),
        encryption=bool(p.get("encryption", False)),
        caching=dict(p.get("caching") or
                     {"keys": "ALL", "rows_per_partition": "NONE"}))
    t = TableMetadata(
        d["keyspace"], d["name"],
        [(n, parse_type(ts, udts)) for n, ts in d["partition_key"]],
        [(n, parse_type(ts, udts), bool(rev))
         for n, ts, rev in d["clustering"]],
        [(n, parse_type(ts, udts)) for n, ts in d["regular"]],
        [(n, parse_type(ts, udts)) for n, ts in d["static"]],
        params, uuid_mod.UUID(d["id"]))
    ids = d.get("column_ids")
    if ids:
        for c in t.static_columns + t.regular_columns:
            if c.name in ids:
                c.column_id = int(ids[c.name])
        t.columns_by_id = {c.column_id: c
                           for c in t.static_columns + t.regular_columns}
    return t


def schema_to_dict(schema: Schema) -> dict:
    out = {"keyspaces": {}}
    for name, ks in schema.keyspaces.items():
        out["keyspaces"][name] = {
            "replication": ks.params.replication,
            "durable_writes": ks.params.durable_writes,
            "user_types": {tn: [(f, repr(ft)) for f, ft in
                                zip(t.field_names, t.elems)]
                           for tn, t in ks.user_types.items()},
            "tables": {tn: table_to_dict(t) for tn, t in ks.tables.items()},
        }
    out["views"] = [{"keyspace": ks, "name": nm, "base": list(v["base"])}
                    for (ks, nm), v in schema.views.items()]
    udfs = getattr(schema, "udfs", None)
    if udfs is not None:
        out["udfs"] = udfs.to_list()
    return out


def load_schema_dict(schema: Schema, data: dict) -> None:
    """Merge a persisted schema dump into `schema` (existing entries win —
    a process-supplied schema takes priority over the disk copy)."""
    from .types.marshal import UserType
    for name, ksd in data.get("keyspaces", {}).items():
        if name not in schema.keyspaces:
            schema.create_keyspace(name, KeyspaceParams(
                replication=ksd["replication"],
                durable_writes=ksd.get("durable_writes", True)))
        ks = schema.keyspaces[name]
        for tn, fields in ksd.get("user_types", {}).items():
            if tn not in ks.user_types:
                ks.user_types[tn] = UserType(
                    name, tn, [f for f, _ in fields],
                    [parse_type(ft, ks.user_types) for _, ft in fields])
        for tn, td in ksd.get("tables", {}).items():
            if tn not in ks.tables:
                schema.add_table(table_from_dict(td, ks.user_types))
    for v in data.get("views", []):
        schema.views.setdefault((v["keyspace"], v["name"]),
                                {"base": tuple(v["base"])})
    if data.get("udfs"):
        from .cql.functions import FunctionRegistry
        if not hasattr(schema, "udfs"):
            schema.udfs = FunctionRegistry()
        schema.udfs.load_list(data["udfs"])


def make_table(keyspace: str, name: str, *, pk: list[str], ck: list[str] = (),
               cols: dict[str, str], desc: set[str] = frozenset(),
               statics: set[str] = frozenset(),
               params: TableParams | None = None) -> TableMetadata:
    """Convenience constructor from type strings, e.g.
    make_table('ks', 't', pk=['id'], ck=['ts'], cols={'id': 'uuid',
    'ts': 'timestamp', 'v': 'text'})."""
    pkc = [(n, parse_type(cols[n])) for n in pk]
    ckc = [(n, parse_type(cols[n]), n in desc) for n in ck]
    other = [(n, parse_type(t)) for n, t in cols.items()
             if n not in pk and n not in ck and n not in statics]
    stat = [(n, parse_type(cols[n])) for n in statics]
    return TableMetadata(keyspace, name, pkc, ckc, other, stat, params)
