"""LockWitness — runtime lock-order witness (the dynamic half of
ctpulint's lock-order check).

The static pass (analysis/checks/lock_order.py) sees syntactic nesting
through an approximate call graph; callbacks, engine-scoped registries
and closures handed across threads are invisible to it. The witness
closes that gap at RUNTIME: instrumented Lock/RLock/Condition wrappers
record, per thread, every "acquired B while holding A" edge into one
process-global order graph, and the first acquisition that would close
a cycle raises `LockOrderError` carrying BOTH stacks — the acquisition
being attempted and the recorded stack that created the reverse path —
so the existing test suite catches dynamic inversions for free, at the
moment they become possible rather than the run they finally deadlock.

Zero-cost when disarmed: the `make_lock/make_rlock/make_condition`
factories return RAW threading primitives unless the witness is armed
at creation time, so production pays nothing — not even a branch per
acquire. Arming therefore only affects locks created AFTER `arm()`:
arm first (tests, scripts/check_static.py full mode, the deterministic
simulator scope), then build the engine. `CTPU_LOCK_WITNESS=1` arms at
import for whole-suite runs.

Identity is the NAME given at the factory (one node per declaration
site, matching the static pass): all instances of `gossip.lock` are one
graph node, so an inversion between two instances of the same class is
caught as an order violation too (conservative, like the static side).
Re-entrant re-acquisition adds no edge; `Condition.wait` releases its
lock for the wait's duration and the held-stack mirrors that.
"""
from __future__ import annotations

import os
import threading
import traceback

__all__ = ["arm", "disarm", "armed", "reset", "make_lock", "make_rlock",
           "make_condition", "LockOrderError", "graph_snapshot"]


class LockOrderError(RuntimeError):
    """Cycle-closing acquisition. The message carries the cycle and
    both stacks (current + the recorded first-creation stack of the
    reverse path's head edge)."""


_armed = os.environ.get("CTPU_LOCK_WITNESS", "") == "1"

_graph_lock = threading.Lock()
# name -> {name -> (thread_name, stack_str)} recorded at first creation
_edges: dict[str, dict[str, tuple]] = {}
_tls = threading.local()


def arm() -> None:
    global _armed
    _armed = True


def disarm() -> None:
    global _armed
    _armed = False


def armed() -> bool:
    return _armed


def reset() -> None:
    """Drop the recorded order graph (test isolation)."""
    with _graph_lock:
        _edges.clear()


def graph_snapshot() -> dict:
    """{holder: [acquired, ...]} — check_static.py prints this after
    the witness-armed smoke."""
    with _graph_lock:
        return {a: sorted(b) for a, b in _edges.items()}


def _held() -> list:
    h = getattr(_tls, "held", None)
    if h is None:
        h = _tls.held = []
    return h


def _find_path(start: str, goal: str) -> list | None:
    """Edge path start→...→goal in the recorded graph (graph lock
    held)."""
    stack = [(start, [start])]
    seen = {start}
    while stack:
        node, path = stack.pop()
        for nxt in _edges.get(node, ()):
            if nxt == goal:
                return path + [goal]
            if nxt not in seen:
                seen.add(nxt)
                stack.append((nxt, path + [nxt]))
    return None


def _stack() -> str:
    return "".join(traceback.format_stack(limit=16)[:-2])


def _record(name: str) -> None:
    """Before blocking on `name`: record edges from every held lock and
    raise if one closes a cycle."""
    held = _held()
    if not held:
        return
    me = threading.current_thread().name
    with _graph_lock:
        for h in held:
            if h == name:
                continue
            # would h -> name close a cycle? i.e. is h reachable FROM
            # name already?
            path = _find_path(name, h)
            if path is not None:
                rev_head = path[0], path[1]
                thread, stack = _edges[rev_head[0]][rev_head[1]]
                cycle = " -> ".join(path + [name])
                raise LockOrderError(
                    f"lock-order cycle closed: acquiring '{name}' "
                    f"while holding '{h}', but the reverse order "
                    f"{cycle} is already recorded.\n"
                    f"--- this acquisition (thread {me}):\n{_stack()}"
                    f"--- recorded '{rev_head[0]}' -> '{rev_head[1]}' "
                    f"(thread {thread}):\n{stack}")
            slot = _edges.setdefault(h, {})
            if name not in slot:
                slot[name] = (me, _stack())


class _WitnessLock:
    """Witnessed threading.Lock. Only exists when created armed."""

    _inner_factory = staticmethod(threading.Lock)
    _reentrant = False

    def __init__(self, name: str):
        self.name = name
        self._inner = self._inner_factory()

    def acquire(self, blocking: bool = True, timeout: float = -1):
        held = _held()
        depth = held.count(self.name)
        if depth == 0 or not self._reentrant:
            _record(self.name)
        got = self._inner.acquire(blocking, timeout) if blocking \
            else self._inner.acquire(False)
        if got:
            held.append(self.name)
        return got

    def release(self) -> None:
        self._inner.release()
        held = _held()
        # remove the innermost occurrence (release order may interleave)
        for i in range(len(held) - 1, -1, -1):
            if held[i] == self.name:
                del held[i]
                break

    def locked(self) -> bool:
        return self._inner.locked()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()


class _WitnessRLock(_WitnessLock):
    _inner_factory = staticmethod(threading.RLock)
    _reentrant = True


class _WitnessCondition:
    """Witnessed threading.Condition over a witnessed (or raw) lock."""

    def __init__(self, name: str, lock=None):
        self.name = name
        self._wlock = lock if lock is not None else _WitnessRLock(name)
        inner = getattr(self._wlock, "_inner", self._wlock)
        self._inner = threading.Condition(inner)

    def acquire(self, *a, **kw):
        return self._wlock.acquire(*a, **kw)

    def release(self):
        self._wlock.release()

    def __enter__(self):
        self._wlock.acquire()
        return self

    def __exit__(self, *exc):
        self._wlock.release()

    def wait(self, timeout: float | None = None):
        # the wait releases the lock: mirror that in the held stack so
        # a notifier path acquiring other locks meanwhile is not seen
        # as nested under ours (all re-entrant depths pop)
        held = _held()
        removed = 0
        for i in range(len(held) - 1, -1, -1):
            if held[i] == self.name:
                del held[i]
                removed += 1
        try:
            return self._inner.wait(timeout)
        finally:
            held.extend([self.name] * removed)

    def wait_for(self, predicate, timeout: float | None = None):
        held = _held()
        removed = 0
        for i in range(len(held) - 1, -1, -1):
            if held[i] == self.name:
                del held[i]
                removed += 1
        try:
            return self._inner.wait_for(predicate, timeout)
        finally:
            held.extend([self.name] * removed)

    def notify(self, n: int = 1):
        self._inner.notify(n)

    def notify_all(self):
        self._inner.notify_all()


def make_lock(name: str):
    """A threading.Lock, witnessed under `name` iff the witness is
    armed right now (zero-cost otherwise: the raw primitive comes
    back)."""
    return _WitnessLock(name) if _armed else threading.Lock()


def make_rlock(name: str):
    return _WitnessRLock(name) if _armed else threading.RLock()


def make_condition(name: str, lock=None):
    if not _armed:
        inner = getattr(lock, "_inner", lock)
        return threading.Condition(inner)
    return _WitnessCondition(name, lock)
