"""Deterministic simulation: virtual time + a seeded event queue that
owns EVERY message delivery, callback timeout, retry sleep, and
background tick in a simulated cluster.

Reference counterpart: the simulator (test/simulator/asm/
InterceptClasses.java role — there via bytecode interception of
monitors/threads; here by construction): the cluster's nondeterminism
sources are funneled through one scheduler so a failing interleaving
REPLAYS byte-for-byte from its seed.

Design — single real thread, inline pumping:
  * `SimTransport.deliver` enqueues the delivery as a virtual-time
    event with a seeded jitter instead of handing it to a per-node
    delivery thread. `MessagingService` detects the sim transport and
    starts no worker/reaper threads; callback timeouts become
    scheduler events (`messaging._expire_one`).
  * Blocking waits (`threading.Event.wait`) become `SimEvent.wait`:
    the caller PUMPS the scheduler inline — processing deliveries,
    timeouts and ticks (possibly re-entrantly triggering nested waits)
    — until its event is set or its virtual deadline passes. One real
    thread, total order chosen only by (virtual time, seeded seq).
  * `time.sleep/monotonic/time/time_ns` in the cluster modules map to
    the virtual clock; `random` in gossip maps to a seeded RNG.
  * Background LOOPS (gossip rounds, hint dispatch) run as recurring
    scheduler timers, never threads: a thread loop would hog the pump.

Within one `simulated(seed)` scope every run of the same scenario
executes the same event sequence; `SimScheduler.trace` records it so
tests can assert replay identity and diff divergent seeds.
"""
from __future__ import annotations

import heapq
import itertools
import random as _random_mod
import threading as _real_threading
import time as _real_time
from contextlib import contextmanager

_MAX_IDLE_ADVANCE = 3600.0     # virtual seconds with an empty queue


class SimScheduler:
    def __init__(self, seed: int):
        self.seed = seed
        self.rng = _random_mod.Random(seed)
        self.now = 0.0                     # virtual seconds
        self._heap: list = []              # (time, seq, fn, desc)
        self._seq = itertools.count()
        self.trace: list[tuple] = []       # (t, seq, desc) as processed
        self.epoch = 1_750_000_000.0       # virtual wall-clock base
        # ONLY this thread may pump: a leaked background thread from
        # earlier tests hitting the patched time.sleep must not drive
        # the queue concurrently — that would corrupt both determinism
        # and the heap (see _FakeTime.sleep's owner guard)
        self.owner = _real_threading.current_thread()

    # ------------------------------------------------------- enqueue --

    def at(self, t: float, fn, desc: str = "") -> None:
        heapq.heappush(self._heap, (max(t, self.now), next(self._seq),
                                    fn, desc))

    def after(self, delay: float, fn, desc: str = "") -> None:
        self.at(self.now + max(delay, 0.0), fn, desc)

    def every(self, interval: float, fn, desc: str = "") -> None:
        """Recurring tick (gossip rounds, hint dispatch)."""
        def tick():
            try:
                fn()
            finally:
                self.after(interval, tick, desc)
        self.after(interval, tick, desc)

    def jitter(self, lo: float = 1e-4, hi: float = 5e-3) -> float:
        """Seeded per-message network delay — the interleaving lever."""
        return self.rng.uniform(lo, hi)

    # ----------------------------------------------------------- pump --

    def step(self) -> bool:
        """Process the single next event; False when the queue is empty."""
        if not self._heap:
            return False
        t, seq, fn, desc = heapq.heappop(self._heap)
        self.now = max(self.now, t)
        self.trace.append((round(t, 9), seq, desc))
        fn()
        return True

    def pump_until(self, pred, deadline: float) -> bool:
        """Process events in order until pred() or virtual `deadline`.
        Re-entrant: events may themselves block on SimEvent.wait, which
        pumps this same queue deeper on the stack."""
        while True:
            if pred():
                return True
            if not self._heap:
                # idle: nothing can ever set pred — advance to deadline
                self.now = min(deadline, self.now + _MAX_IDLE_ADVANCE)
                return pred()
            t = self._heap[0][0]
            if t > deadline:
                self.now = deadline
                return pred()
            self.step()

    def run(self, duration: float) -> None:
        """Advance virtual time by `duration`, draining due events."""
        end = self.now + duration
        self.pump_until(lambda: False, end)

    def drain(self, max_events: int = 100_000) -> None:
        """Run until the queue is empty (recurring timers excluded by
        cancelling them first) or the event budget trips."""
        n = 0
        while self._heap and n < max_events:
            self.step()
            n += 1


class SimEvent:
    """threading.Event whose wait() pumps the scheduler (virtual time)
    instead of blocking a real thread."""

    def __init__(self, sched: SimScheduler):
        self._sched = sched
        self._set = False

    def set(self) -> None:
        self._set = True

    def clear(self) -> None:
        self._set = False

    def is_set(self) -> bool:
        return self._set

    def wait(self, timeout: float | None = None) -> bool:
        if _real_threading.current_thread() is not self._sched.owner:
            # foreign threads may not pump; poll in real time instead
            deadline = _real_time.monotonic() + (timeout or 60.0)
            while not self._set and _real_time.monotonic() < deadline:
                _real_time.sleep(0.01)
            return self._set
        deadline = self._sched.now + (1e12 if timeout is None
                                      else max(timeout, 0.0))
        return self._sched.pump_until(self.is_set, deadline)


class SimCondition:
    """threading.Condition whose wait() pumps the scheduler (virtual
    time). The underlying lock stays a REAL RLock — the single pumping
    thread holds it re-entrancy-safely — and wait() releases it while
    pumping so events fired by the pump (acks, failures) can take it
    to notify."""

    def __init__(self, sched: SimScheduler, lock=None):
        self._sched = sched
        self._lock = lock if lock is not None else _real_threading.RLock()
        self._seq = 0   # bumped per notify; waiters watch for a change

    def acquire(self, *a, **kw):
        return self._lock.acquire(*a, **kw)

    def release(self):
        self._lock.release()

    def __enter__(self):
        self._lock.acquire()
        return self

    def __exit__(self, *exc):
        self._lock.release()

    def notify(self, n: int = 1) -> None:
        self._seq += 1

    def notify_all(self) -> None:
        self._seq += 1

    def wait(self, timeout: float | None = None) -> bool:
        start = self._seq
        if _real_threading.current_thread() is not self._sched.owner:
            # foreign threads may not pump; poll in real time instead
            deadline = _real_time.monotonic() + (timeout or 60.0)
            self._lock.release()
            try:
                while self._seq == start and \
                        _real_time.monotonic() < deadline:
                    _real_time.sleep(0.01)
            finally:
                self._lock.acquire()
            return self._seq != start
        deadline = self._sched.now + (1e12 if timeout is None
                                      else max(timeout, 0.0))
        self._lock.release()
        try:
            return self._sched.pump_until(lambda: self._seq != start,
                                          deadline)
        finally:
            self._lock.acquire()

    def wait_for(self, predicate, timeout: float | None = None):
        deadline = None if timeout is None else self._sched.now + timeout
        result = predicate()
        while not result:
            if deadline is not None and self._sched.now >= deadline:
                break
            self.wait(None if deadline is None
                      else deadline - self._sched.now)
            result = predicate()
        return result


class SimThread:
    """threading.Thread stand-in: the target runs as ONE scheduled
    event on the pumping thread (it may itself block via SimEvent,
    nesting the pump). Loop bodies must NOT use this — drive them with
    SimScheduler.every instead."""

    def __init__(self, sched: SimScheduler, target=None, args=(),
                 kwargs=None, daemon=None, name=None):
        self._sched = sched
        self._target = target
        self._args = args
        self._kwargs = kwargs or {}
        self._done = False
        self.name = name or "sim-thread"
        self.daemon = daemon

    def start(self) -> None:
        def run():
            try:
                if self._target is not None:
                    self._target(*self._args, **self._kwargs)
            finally:
                self._done = True
        self._sched.after(self._sched.jitter(), run,
                          f"thread:{self.name}")

    def join(self, timeout: float | None = None) -> None:
        self._sched.pump_until(lambda: self._done,
                               self._sched.now + (timeout or 1e12))

    def is_alive(self) -> bool:
        return not self._done


class _FakeThreading:
    """Module-attribute replacement for `threading` inside simulated
    cluster modules: Event/Thread become scheduler-driven; locks stay
    real (a single pumping thread holds them re-entrancy-safely via the
    same discipline as production — blocking waits never happen while a
    plain Lock is held)."""

    def __init__(self, sched: SimScheduler):
        self._sched = sched
        self.Lock = _real_threading.Lock
        self.RLock = _real_threading.RLock
        self.local = _real_threading.local
        self.current_thread = _real_threading.current_thread

    def Event(self):
        return SimEvent(self._sched)

    def Condition(self, lock=None):
        return SimCondition(self._sched, lock)

    def Thread(self, target=None, args=(), kwargs=None, daemon=None,
               name=None):
        return SimThread(self._sched, target=target, args=args,
                         kwargs=kwargs, daemon=daemon, name=name)


class _FakeTime:
    """Module-attribute replacement for `time`: virtual clock."""

    def __init__(self, sched: SimScheduler):
        self._sched = sched

    def monotonic(self) -> float:
        return self._sched.now

    def perf_counter(self) -> float:
        return self._sched.now

    def time(self) -> float:
        return self._sched.epoch + self._sched.now

    def time_ns(self) -> int:
        return int((self._sched.epoch + self._sched.now) * 1e9)

    def sleep(self, seconds: float) -> None:
        if _real_threading.current_thread() is not self._sched.owner:
            # a foreign (leaked/background) thread must never pump the
            # scheduler — give it a bounded real sleep instead
            _real_time.sleep(min(seconds, 0.05))
            return
        self._sched.run(seconds)


# modules whose top-level `threading`/`time`/`random` are redirected
# while a simulation is active
_PATCH_MODULES = (
    "cassandra_tpu.cluster.messaging",
    "cassandra_tpu.cluster.coordinator",
    "cassandra_tpu.cluster.schema_sync",
    "cassandra_tpu.cluster.cms",
    "cassandra_tpu.cluster.paxos",
    "cassandra_tpu.cluster.gossip",
    "cassandra_tpu.cluster.node",
    "cassandra_tpu.cluster.counters",
    "cassandra_tpu.cluster.repair",
)


@contextmanager
def simulated(seed: int):
    """Activate deterministic simulation: patches the cluster modules'
    time/threading/random onto a fresh SimScheduler, yields it, and
    restores everything on exit. Build nodes INSIDE the scope (their
    Events must be SimEvents) — or use SimCluster, which does."""
    import importlib

    from ..utils import lockwitness

    sched = SimScheduler(seed)
    fthreading = _FakeThreading(sched)
    ftime = _FakeTime(sched)
    # the lock-order witness is armed for the scope: every witnessed
    # lock the simulated cluster creates records acquisition edges, and
    # a cycle-closing acquisition raises deterministically (same seed →
    # same event order → same first-cycle edge). The graph resets at
    # entry so a replay of the same seed sees the same empty graph —
    # UNLESS the witness was already armed externally
    # (CTPU_LOCK_WITNESS=1 whole-suite runs): wiping the accumulated
    # process-global graph there would silently drop edges other tests
    # recorded, degrading whole-suite coverage to per-scope coverage.
    _witness_was_armed = lockwitness.armed()
    if not _witness_was_armed:
        lockwitness.reset()
        lockwitness.arm()
    saved: list[tuple] = []
    for name in _PATCH_MODULES:
        mod = importlib.import_module(name)
        for attr, repl in (("threading", fthreading), ("time", ftime)):
            if hasattr(mod, attr):
                saved.append((mod, attr, getattr(mod, attr)))
                setattr(mod, attr, repl)
    # TTL expiry and write-time now-seconds follow the virtual clock too
    from ..utils import timeutil
    saved.append((timeutil, "CLOCK", timeutil.CLOCK))
    timeutil.CLOCK = ftime.time
    try:
        yield sched
    finally:
        if not _witness_was_armed:
            lockwitness.disarm()
        for mod, attr, orig in reversed(saved):
            setattr(mod, attr, orig)


class SimTransport:
    """LocalTransport-shaped transport whose deliveries are scheduler
    events with seeded jitter (the nondeterminism lever). Carries the
    scheduler so MessagingService skips its threads."""

    def __init__(self, scheduler: SimScheduler):
        from ..cluster.messaging import MessageFilters
        self.scheduler = scheduler
        self.filters = MessageFilters()
        self._nodes: dict = {}

    def register(self, ep, svc) -> None:
        self._nodes[ep] = svc

    def unregister(self, ep) -> None:
        self._nodes.pop(ep, None)

    def deliver(self, msg) -> None:
        if self.filters.should_drop(msg):
            return

        def run():
            target = self._nodes.get(msg.to)
            if target is not None and not target.closed:
                target._process(msg)
        self.scheduler.after(
            self.scheduler.jitter(), run,
            f"{msg.verb} {msg.sender.name}->{msg.to.name}#{msg.id}")


class SimCluster:
    """N nodes in the noded deployment shape (per-node Schema/Ring/
    SchemaSync) over a SimTransport, with gossip + hint dispatch as
    recurring scheduler timers. Must be constructed inside a
    simulated(seed) scope."""

    def __init__(self, sched: SimScheduler, base_dir: str, n: int = 3,
                 gossip_interval: float = 0.25, schema_sync: bool = True):
        import os

        from ..cluster.node import Node
        from ..cluster.ring import Endpoint, Ring, even_tokens
        from ..cluster.schema_sync import SchemaSync
        from ..schema import Schema
        self.sched = sched
        self.transport = SimTransport(sched)
        self.eps = [Endpoint(f"node{i + 1}", host="127.0.0.1", port=0)
                    for i in range(n)]
        tokens = even_tokens(n, vnodes=4)
        self.nodes = []
        for ep in self.eps:
            ring = Ring()
            for e, toks in zip(self.eps, tokens):
                ring.add_node(e, toks)
            node = Node(ep, os.path.join(base_dir, ep.name), Schema(),
                        ring, self.transport, seeds=[self.eps[0]],
                        gossip_interval=gossip_interval)
            node.cluster_nodes = [node]
            # the Node constructor's hint thread became a no-op SimThread
            # loop; stop it and drive dispatch as a timer instead
            node._stop_hints.set()
            sched.every(0.5, node.hint_round, f"hints:{ep.name}")
            node.gossiper.clock = lambda: sched.now
            # per-node seeded RNG: gossip target selection replays
            # (and no foreign thread can consume our draws)
            node.gossiper.rng = __import__("random").Random(
                (sched.seed << 8) ^ len(self.nodes))
            sched.every(gossip_interval, node.gossiper.round,
                        f"gossip:{ep.name}")
            if schema_sync:
                node.schema_sync = SchemaSync(
                    node, os.path.join(base_dir, ep.name))
            self.nodes.append(node)
        # seed full mutual liveness (LocalCluster does the same)
        from ..cluster.gossip import EndpointState
        for node in self.nodes:
            for other in self.nodes:
                if other.endpoint != node.endpoint:
                    st = node.gossiper.states.setdefault(
                        other.endpoint, EndpointState(generation=1))
                    node.gossiper.detector.report(other.endpoint, st,
                                                  sched.now)

    @property
    def filters(self):
        return self.transport.filters

    def node(self, i: int):
        return self.nodes[i - 1]

    def session(self, i: int = 1):
        return self.nodes[i - 1].session()

    def partition(self, *eps):
        """Cut the given endpoints off from the rest, both directions."""
        rules = []
        for ep in eps:
            rules.append(self.filters.drop(frm=ep))
            rules.append(self.filters.drop(to=ep))
        return rules

    def shutdown(self):
        for n in self.nodes:
            try:
                n.engine.close()
            except Exception:
                pass
