"""Metrics history: the retained-time-series layer of the observatory.

Reference counterpart: none in-tree — the reference exports point-in-
time metrics over JMX and leaves retention to external scrapers. The
ADAPTIVE compaction controller (ROADMAP item 4) cannot depend on an
external Prometheus: closing the loop on observed read/write/space
amplification needs history the node itself retains, which the LSM
design-space survey (arXiv 2202.04522) frames as the tuner's primary
input signal.

`MetricsHistoryService` (engine-scoped, like the flight recorder):

- A fixed-interval sampler with an injectable clock. Each `sample()`
  captures one flat {name: number} view — the global metrics registry
  snapshot (counters, gauges, histogram summaries), this engine's
  compaction gauges, every store's per-table counters and the derived
  amplification gauges — and appends it to per-series rings.
- **Multi-resolution rings**: the raw ring keeps `raw_capacity`
  samples (360 × the 10 s default interval ≈ 1 hour); every
  `raw_per_coarse` raw samples seal into one coarse bucket
  (min/max/last/sum/n-preserving merge, 288 kept ≈ 24 h at the
  defaults). Raw eviction never loses coarse history — buckets fold at
  sample time, not at eviction time.
- `rate()` derives a per-second rate between consecutive retained raw
  samples of a (monotonic) counter; a negative delta — a counter reset
  across an engine restart — clamps to 0 instead of reporting a
  nonsense negative rate.
- **Zero-cost when off** (the diagnostic-bus rule): while the mutable
  `metrics_history_enabled` knob is false no sampler thread exists and
  nothing is captured; `sample()` stays callable on demand (the flight
  recorder takes one moment-of sample at dump time so a bundle always
  carries a history window). The knob is ENGINE-scoped: each engine
  owns its service, so a co-hosted node's knob never flips a peer's
  sampler.

Surfaces: `system_views.metrics_history`, `nodetool metricshistory`,
the `metrics_history` window in every flight-recorder bundle, and the
`history.samples` counter. `bench.py`'s `observatory` section proves
the sampler's overhead share of a compaction run.
"""
from __future__ import annotations

import threading
import time

# ctpulint: clock-injectable
# every timestamp and duration in this module comes from the service's
# injected clock; `time.monotonic` appears only as the production
# default (a reference, never a direct call)

from collections import deque

from .metrics import GLOBAL as METRICS


class _Series:
    """One metric's retained history: a raw ring of (t, value) samples
    plus a coarse ring of sealed merge buckets. Mutated only under the
    owning service's lock."""

    __slots__ = ("raw", "coarse", "acc")

    def __init__(self, raw_capacity: int, coarse_capacity: int):
        self.raw: deque = deque(maxlen=raw_capacity)
        self.coarse: deque = deque(maxlen=coarse_capacity)
        self.acc: dict | None = None   # open (unsealed) coarse bucket

    def add(self, t: float, v: float, raw_per_coarse: int) -> None:
        self.raw.append((t, v))
        a = self.acc
        if a is None:
            self.acc = {"t0": t, "t1": t, "min": v, "max": v,
                        "last": v, "sum": v, "n": 1}
        else:
            a["t1"] = t
            if v < a["min"]:
                a["min"] = v
            if v > a["max"]:
                a["max"] = v
            a["last"] = v
            a["sum"] += v
            a["n"] += 1
        if self.acc["n"] >= raw_per_coarse:
            self.coarse.append(self.acc)
            self.acc = None


class MetricsHistoryService:
    """Engine-scoped retained metrics history (see module docstring).
    All ring state is guarded by one lock; `sample()` collects OUTSIDE
    the lock (registry snapshots serialize on their own locks) and
    folds under it."""

    RAW_CAPACITY = 360        # 1 h at the 10 s default interval
    RAW_PER_COARSE = 30       # one coarse bucket per 5 min of raw
    COARSE_CAPACITY = 288     # ≈ 24 h of coarse history

    MIN_INTERVAL_S = 0.05   # floor shared by __init__ and set_interval:
    #                         a 0-second knob must not boot a busy-spin
    #                         sampler thread

    def __init__(self, engine=None, clock=time.monotonic,
                 interval_s: float = 10.0,
                 raw_capacity: int | None = None,
                 raw_per_coarse: int | None = None,
                 coarse_capacity: int | None = None,
                 collect_fn=None, wall_clock=time.time):
        self.engine = engine
        self.clock = clock
        # wall-clock reference for rendering surfaces (the vtable's
        # at_ms must be epoch-comparable with telemetry snapshots and
        # diagnostic events); sampling arithmetic stays on the
        # injectable monotonic clock
        self.wall_clock = wall_clock
        self._wall_offset: float | None = None
        self.interval_s = max(float(interval_s), self.MIN_INTERVAL_S)
        self.raw_capacity = int(raw_capacity or self.RAW_CAPACITY)
        self.raw_per_coarse = int(raw_per_coarse or self.RAW_PER_COARSE)
        self.coarse_capacity = int(coarse_capacity
                                   or self.COARSE_CAPACITY)
        # injectable capture source (tests / check_observatory.py
        # determinism); default reads the live registries
        self._collect_fn = collect_fn
        self._lock = threading.Lock()
        self._series: dict[str, _Series] = {}
        self.samples = 0             # lifetime sample() calls
        self.sample_seconds = 0.0    # cumulative capture cost (the
        #                              bench overhead numerator)
        self._stop: threading.Event | None = None
        self._wake: threading.Event | None = None
        self._thread: threading.Thread | None = None

    # ------------------------------------------------------------ config --

    @property
    def enabled(self) -> bool:
        return self._thread is not None and self._thread.is_alive()

    def set_enabled(self, on) -> None:
        """The `metrics_history_enabled` knob landing: start or stop
        the sampler thread. Retained rings survive a disable — history
        up to the stop stays queryable."""
        if on:
            self.start()
        else:
            self.stop()

    def set_interval(self, seconds: float) -> None:
        """The `metrics_history_interval` knob: a parked sampler is
        woken so the new period applies NOW, not after the old one
        elapses."""
        self.interval_s = max(float(seconds), self.MIN_INTERVAL_S)
        wake = self._wake
        if wake is not None:
            wake.set()

    # ------------------------------------------------------------ sampler --

    def start(self) -> None:
        """Idempotent sampler start (daemon thread, the SLO poller
        shape)."""
        if self.enabled:
            return
        stop = threading.Event()
        wake = threading.Event()
        self._stop = stop
        self._wake = wake

        def _run():
            while not stop.is_set():
                try:
                    if wake.wait(self.interval_s):
                        wake.clear()   # interval kick: re-read the
                        continue       # new period, no sample yet
                    self.sample()
                except Exception:
                    pass   # a broken gauge must not kill the sampler

        self._thread = threading.Thread(target=_run,
                                        name="metrics-history",
                                        daemon=True)
        self._thread.start()

    def stop(self) -> None:
        if self._stop is not None:
            self._stop.set()
        if self._wake is not None:
            self._wake.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
        self._thread = None
        self._stop = None
        self._wake = None

    close = stop

    # ------------------------------------------------------------- sample --

    def _default_collect(self) -> dict:
        """One flat {name: number} capture: global registry snapshot +
        this engine's compaction gauges + per-table counters and the
        derived amplification gauges."""
        out = {}
        for k, v in METRICS.snapshot().items():
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                out[k] = float(v)
        eng = self.engine
        if eng is not None:
            try:
                out.update(eng.compactions.gauges())
            except Exception:
                pass
            for cfs in list(eng.stores.values()):
                base = f"table.{cfs.table.keyspace}.{cfs.table.name}"
                for k, v in cfs.metrics.items():
                    out[f"{base}.{k}"] = float(v)
                try:
                    for k, v in cfs.amplification().items():
                        out[f"{base}.{k}"] = float(v)
                except Exception:
                    pass
        return out

    def sample(self) -> int:
        """Take one capture NOW (on-demand callers — the flight
        recorder's dump-time sample, nodetool, tests — need no running
        sampler). Returns the number of series updated."""
        t0 = self.clock()
        values = (self._collect_fn or self._default_collect)()
        t = self.clock()
        with self._lock:
            # latest service-clock → wall-clock mapping (rendering
            # surfaces only; bucket arithmetic stays monotonic)
            self._wall_offset = self.wall_clock() - t
            for name, v in values.items():
                s = self._series.get(name)
                if s is None:
                    s = self._series[name] = _Series(
                        self.raw_capacity, self.coarse_capacity)
                s.add(t, float(v), self.raw_per_coarse)
            self.samples += 1
            self.sample_seconds += max(t - t0, 0.0) \
                + max(self.clock() - t, 0.0)
        METRICS.incr("history.samples")
        return len(values)

    # -------------------------------------------------------------- query --

    def names(self) -> list[str]:
        with self._lock:
            return sorted(self._series)

    def query(self, name: str, resolution: str = "raw",
              limit: int | None = None) -> list[dict]:
        """Retained buckets for one series, oldest first. `raw` rows
        are single samples rendered in the bucket shape (min == max ==
        last == sum, n == 1); `coarse` rows are the sealed
        min/max/last/sum-preserving merge buckets (the open accumulator
        is excluded — it is still absorbing raw samples)."""
        if resolution not in ("raw", "coarse"):
            raise ValueError(f"unknown resolution {resolution!r}")
        with self._lock:
            s = self._series.get(name)
            if s is None:
                return []
            if resolution == "raw":
                rows = [{"t0": t, "t1": t, "min": v, "max": v,
                         "last": v, "sum": v, "n": 1}
                        for t, v in s.raw]
            else:
                rows = [dict(b) for b in s.coarse]
        return rows[-limit:] if limit else rows

    def rate(self, name: str, limit: int | None = None) -> list[dict]:
        """Per-second rate between consecutive retained raw samples of
        a counter: [(t, (v_i − v_{i−1}) / (t_i − t_{i−1}))]. A negative
        delta (counter reset) clamps to 0.0; zero-dt pairs are
        skipped. Ring eviction only shortens the window — rates are
        always between samples that were actually retained."""
        with self._lock:
            s = self._series.get(name)
            pts = list(s.raw) if s is not None else []
        out = []
        for (t0, v0), (t1, v1) in zip(pts, pts[1:]):
            dt = t1 - t0
            if dt <= 0:
                continue
            out.append({"t": t1, "per_s": max(v1 - v0, 0.0) / dt})
        return out[-limit:] if limit else out

    def recent_window(self, max_points: int = 30) -> dict:
        """The flight-recorder bundle view: {name: [[t, value], ...]},
        the newest `max_points` raw samples per series — what *led up
        to* the event, bounded."""
        with self._lock:
            return {name: [[t, v] for t, v in
                           list(s.raw)[-max_points:]]
                    for name, s in self._series.items() if s.raw}

    def to_wall(self, t: float) -> float:
        """Map a bucket's service-clock time onto the wall clock (epoch
        seconds) using the offset captured at the most recent sample —
        so vtable timestamps join against telemetry snapshots and
        diagnostic events. Identity before the first sample."""
        off = self._wall_offset
        return t if off is None else t + off

    def stats(self) -> dict:
        with self._lock:
            return {"enabled": self.enabled,
                    "interval_s": self.interval_s,
                    "series": len(self._series),
                    "samples": self.samples,
                    "sample_seconds": round(self.sample_seconds, 6)}
