"""QueryProcessor: parse -> prepare cache -> execute; plus the Session
facade users interact with.

Reference counterpart: cql3/QueryProcessor.java:109 (processStatement:276,
parseStatement:382, MD5-keyed prepared cache) and the driver Session
surface.
"""
from __future__ import annotations

import hashlib
import threading
from collections import OrderedDict

from .execution import Executor, InvalidRequest, ResultSet
from .parser import parse

# registry bound when the backend carries no settings (the
# prepared_statements_cache_size knob overrides; <= 0 = unbounded)
DEFAULT_PREPARED_CACHE_SIZE = 1024


class Prepared:
    def __init__(self, statement, query: str):
        self.statement = statement
        self.query = query


class QueryProcessor:
    def __init__(self, backend):
        self.executor = Executor(backend)
        # LRU, bounded by prepared_statements_cache_size: a PREPARE storm
        # (or a client generating unique statements) can no longer grow
        # the registry without limit. Eviction counts
        # `prepared_statements.evicted`; executing an evicted id raises
        # here and maps to the wire UNPREPARED error in the transport so
        # drivers transparently re-prepare (QueryProcessor.java's
        # capacity-bounded preparedStatements cache).
        self._prepared: "OrderedDict[bytes, Prepared]" = OrderedDict()
        self._lock = threading.Lock()

    def parse(self, query: str):
        return parse(query)

    def _prepared_cap(self) -> int:
        settings = getattr(self.executor.backend, "settings", None)
        if settings is None:
            return DEFAULT_PREPARED_CACHE_SIZE
        try:
            return int(settings.get("prepared_statements_cache_size"))
        except Exception:
            return DEFAULT_PREPARED_CACHE_SIZE

    def prepare(self, query: str) -> bytes:
        """Returns the statement id (MD5 of the query, like the reference)."""
        return self.prepare_full(query)[0]

    def prepare_full(self, query: str) -> tuple[bytes, Prepared]:
        """(qid, Prepared) — the object is returned from UNDER the
        registry lock so a concurrent PREPARE storm evicting this very
        entry can't leave the caller describing a statement it can no
        longer see (the transport builds the bind metadata from it)."""
        qid = hashlib.md5(query.encode()).digest()
        evicted = 0
        with self._lock:
            prep = self._prepared.get(qid)
            if prep is None:
                prep = self._prepared[qid] = Prepared(parse(query), query)
            else:
                self._prepared.move_to_end(qid)
            cap = self._prepared_cap()
            while cap > 0 and len(self._prepared) > cap:
                self._prepared.popitem(last=False)
                evicted += 1
        if evicted:
            from ..service.metrics import GLOBAL
            GLOBAL.incr("prepared_statements.evicted", evicted)
        return qid, prep

    def get_prepared(self, qid: bytes) -> Prepared | None:
        """LRU-touching lookup (None = never prepared OR evicted; the
        caller decides between InvalidRequest and wire UNPREPARED)."""
        with self._lock:
            prep = self._prepared.get(qid)
            if prep is not None:
                self._prepared.move_to_end(qid)
            return prep

    def execute_prepared(self, qid: bytes, params=(),
                         keyspace: str | None = None,
                         user: str | None = None,
                         page_size: int | None = None,
                         paging_state: bytes | None = None) -> ResultSet:
        prep = self.get_prepared(qid)
        if prep is None:
            raise InvalidRequest("unknown prepared statement")
        return self.execute_statement(prep, params, keyspace, user=user,
                                      page_size=page_size,
                                      paging_state=paging_state)

    def execute_statement(self, prep: Prepared, params=(),
                          keyspace: str | None = None,
                          user: str | None = None,
                          page_size: int | None = None,
                          paging_state: bytes | None = None) -> ResultSet:
        """Execute an already-resolved Prepared. The transport fetches
        the Prepared ONCE (for the UNPREPARED check and verb
        classification) and executes that same object — no second
        lookup that could race LRU eviction into the wrong error."""
        audit = getattr(self.executor.backend, "audit_log", None)
        if audit is not None:
            audit.log(type(prep.statement).__name__, prep.query, user,
                      keyspace, params=params)
        fql = getattr(self.executor.backend, "fql_log", None)
        if fql is not None:
            fql.log(type(prep.statement).__name__, prep.query, user,
                    keyspace, params=params)
        sync = self._ddl_sync_for(prep.statement)
        if sync is not None:
            # prepared DDL replicates exactly like direct DDL — a
            # bypass here would apply locally only, with no epoch
            self._check_ddl_auth(prep.statement, keyspace, user)
            from ..service.metrics import GLOBAL
            with GLOBAL.timer("cql.request"):
                return sync.coordinate(prep.query, keyspace,
                                       prep.statement)
        return self.executor.execute(prep.statement, params, keyspace,
                                     user=user, page_size=page_size,
                                     paging_state=paging_state)

    def _ddl_sync_for(self, stmt):
        """The schema-sync service, iff `stmt` is DDL that must
        replicate through the epoch log (TCM-lite); else None."""
        sync = getattr(self.executor.backend, "schema_sync", None)
        if sync is None:
            return None
        from ..cluster.schema_sync import DDL_STATEMENTS
        return sync if type(stmt).__name__ in DDL_STATEMENTS else None

    def _check_ddl_auth(self, stmt, keyspace, user) -> None:
        """Permission check for log-replicated DDL. Under
        commit-then-apply the coordinator no longer executes the
        statement through Executor.execute (whose auth gate covers the
        non-replicated path), so the same check runs here BEFORE the
        statement reaches the metadata log."""
        auth = getattr(self.executor.backend, "auth", None)
        if auth is None or not auth.enabled:
            return
        perm = Executor.PERMISSION_OF.get(type(stmt).__name__)
        if perm is not None:
            ks = getattr(stmt, "keyspace", None) or keyspace
            auth.check(user, perm, ks)

    def process(self, query: str, params=(),
                keyspace: str | None = None,
                user: str | None = None, page_size: int | None = None,
                paging_state: bytes | None = None) -> ResultSet:
        import time as time_mod

        from ..service.metrics import GLOBAL
        # per-phase walls for the slow-query log: parse / execute /
        # serialize (result assembly after the executor returns) — a
        # slow entry says WHERE it was slow, not just how slow
        t0 = time_mod.perf_counter()
        phases: dict = {}
        stmt = parse(query)
        phases["parse"] = time_mod.perf_counter() - t0
        kind = type(stmt).__name__.removesuffix("Statement").lower()
        GLOBAL.incr(f"cql.{kind}")
        audit = getattr(self.executor.backend, "audit_log", None)
        if audit is not None:
            audit.log(type(stmt).__name__, query, user, keyspace,
                      params=params)
        fql = getattr(self.executor.backend, "fql_log", None)
        if fql is not None:
            fql.log(type(stmt).__name__, query, user, keyspace,
                    params=params)
        try:
            t_exec = time_mod.perf_counter()
            sync = self._ddl_sync_for(stmt)
            if sync is not None:
                self._check_ddl_auth(stmt, keyspace, user)
                with GLOBAL.timer("cql.request"):
                    try:
                        return sync.coordinate(query, keyspace, stmt)
                    finally:
                        # recorded on the raise path too: a timed-out
                        # statement must attribute its wall to execute
                        phases["execute"] = \
                            time_mod.perf_counter() - t_exec
            with GLOBAL.timer("cql.request"):
                try:
                    rs = self.executor.execute(
                        stmt, params, keyspace, user=user,
                        page_size=page_size,
                        paging_state=paging_state)
                finally:
                    t_ser = time_mod.perf_counter()
                    phases["execute"] = t_ser - t_exec
                # result materialization cost (rows already decoded by
                # the executor; anything lazy the ResultSet does to
                # render row tuples lands here)
                _ = getattr(rs, "rows", None)
                phases["serialize"] = time_mod.perf_counter() - t_ser
                return rs
        finally:
            mon = getattr(self.executor.backend, "monitor", None)
            if mon is not None:
                from ..service import tracing
                # a slow statement that was traced links to its timeline
                # (system_views.slow_queries.trace_session)
                mon.record(query, time_mod.perf_counter() - t0,
                           keyspace,
                           trace_session=tracing.current_id(),
                           phases=phases)


class Session:
    """User-facing session: execute CQL strings against a backend
    (StorageEngine locally; a coordinator in a cluster)."""

    def __init__(self, backend, keyspace: str | None = None,
                 user: str | None = None, password: str | None = None):
        self.processor = QueryProcessor(backend)
        self.keyspace = keyspace
        self.user = None
        auth = getattr(backend, "auth", None)
        if auth is not None and auth.enabled:
            if user is None:
                raise ValueError("this backend requires authentication")
            self.user = auth.authenticate(user, password or "")

    def execute(self, query: str, params=(), trace: bool = False,
                fetch_size: int | None = None,
                paging_state: bytes | None = None) -> ResultSet:
        """fetch_size pages large scans: the ResultSet carries at most
        fetch_size rows plus .paging_state to pass back for the next page
        (driver-style paging).

        Tracing: trace=True opens an explicit session (cqlsh TRACING ON)
        and attaches it to the result. Otherwise the backend's mutable
        `trace_probability` setting (nodetool settraceprobability) is
        consulted: sampled statements trace in the background, landing in
        the backend's TraceStore only — the result set stays untouched.
        Either way the session persists to the store even when the
        statement RAISES (a timed-out read still renders its timeline)."""
        from ..service import tracing
        backend = self.processor.executor.backend
        st = None
        if trace:
            st = tracing.begin(request=query[:200])
            tracing.trace(f"Parsing {query[:60]}")
        else:
            settings = getattr(backend, "settings", None)
            if settings is not None and tracing.should_sample(
                    settings.get("trace_probability")):
                st = tracing.begin(request=query[:200])
                tracing.trace(
                    f"Sampled by trace_probability: {query[:60]}")
        try:
            rs = self.processor.process(query, params, self.keyspace,
                                        user=self.user,
                                        page_size=fetch_size,
                                        paging_state=paging_state)
        finally:
            if st is not None:
                tracing.end()
                store = getattr(backend, "trace_store", None)
                if store is not None:
                    store.save(st)
        if trace:
            rs.trace = st
        if hasattr(rs, "keyspace"):
            self.keyspace = rs.keyspace
        return rs

    def prepare(self, query: str) -> bytes:
        return self.processor.prepare(query)

    def execute_prepared(self, qid: bytes, params=()) -> ResultSet:
        return self.processor.execute_prepared(qid, params, self.keyspace,
                                               user=self.user)
