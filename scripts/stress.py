#!/usr/bin/env python
"""cassandra-stress-style multi-connection WIRE driver.

Reference counterpart: tools/stress/ (Stress.java) driving the native
protocol over real sockets — unlike tools/stress.py (which calls a
Session in-process), every operation here crosses the event-loop server
(cassandra_tpu/transport/): prepared statements, admission control,
per-client rate limiting and the v5 segment framing are all on the path.

Workloads: write / read / mixed (--write-ratio) over a fixed integer
key space, keys drawn uniform / zipf (hot-partition skew) / sequential
(disjoint per-connection ranges — deterministic, the smoke mode's
correctness base). One OS thread per connection issues synchronous
requests, so `--connections` IS the offered concurrency; latencies land
in a shared service/metrics.LatencyHistogram (the same decaying
histogram the server exports) plus exact numpy percentiles.

Errors are classified by wire code: OVERLOADED (0x1001) shed by the
permit gate / overload signals vs rate-limited (same code, rate-limit
message) vs UNPREPARED (0x2500) vs other. The caller decides whether
they are failures: the bench's overload run REQUIRES them.

`--smoke` is the tier-2 drill (exit 1 on violation, seconds-long,
deterministic; CI runs it alongside chaos_storage.py): in-process
server, then (1) concurrent writes land and read back exactly,
(2) serving 64 connections creates no new server threads (the
event-loop contract), (3) with the permit cap pinched the server sheds
with OVERLOADED while in-flight never exceeds the cap and the server
stays responsive, (4) the per-client rate limiter sheds and hot-reloads
off again.

Usage:
  python scripts/stress.py --profile mixed --connections 64 --ops 8192
  python scripts/stress.py --host 10.0.0.5 --port 9042 --profile read
  python scripts/stress.py --smoke
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

KEYSPACE = "stress"
TABLE = "frontdoor"
DDL = (f"CREATE KEYSPACE IF NOT EXISTS {KEYSPACE} WITH replication = "
       "{'class': 'SimpleStrategy', 'replication_factor': 1}",
       f"CREATE TABLE IF NOT EXISTS {KEYSPACE}.{TABLE} "
       "(key int PRIMARY KEY, v blob)")
INSERT = f"INSERT INTO {KEYSPACE}.{TABLE} (key, v) VALUES (?, ?)"
SELECT = f"SELECT v FROM {KEYSPACE}.{TABLE} WHERE key = ?"


def _client_table():
    """Client-side mirror of the stress table for wire serialization
    (the driver serializes bind values against CQL types itself)."""
    from cassandra_tpu.schema import make_table
    return make_table(KEYSPACE, TABLE, pk=["key"],
                      cols={"key": "int", "v": "blob"})


def _classify(msg: str) -> str:
    if "0x1001" in msg:
        return "rate_limited" if "rate limit" in msg.lower() \
            else "overloaded"
    if "0x2500" in msg:
        return "unprepared"
    return "other"


def _keys(dist: str, n: int, key_space: int, rng, worker: int,
          workers: int) -> np.ndarray:
    if dist == "sequential":
        # disjoint per-connection ranges: deterministic coverage of
        # [0, workers*n) — the smoke read-back check depends on it
        return np.arange(n) + worker * n
    if dist == "zipf":
        # zipf-skewed hot partitions clipped into the key space
        return np.minimum(rng.zipf(1.3, n), key_space) - 1
    return rng.integers(0, key_space, n)


def _worker(idx: int, host: str, port: int, profile: str, n_ops: int,
            dist: str, key_space: int, value_bytes: int,
            write_ratio: float, seed: int, workers: int, hist,
            barrier, results: list) -> None:
    from cassandra_tpu.client import Cluster, DriverError, \
        serialize_params
    rng = np.random.default_rng(seed * 100_000 + idx)
    table = _client_table()
    lats: list = []
    errs: dict = {}
    ok = 0
    # connect + prepare BEFORE the barrier so every worker reaches it
    # exactly once (a broken barrier strands the whole run); a failed
    # connection just records itself and sits the run out
    sess = None
    try:
        sess = Cluster(host, port).connect()
        wq = sess.prepare(INSERT)
        rq = sess.prepare(SELECT)
    except Exception as e:
        errs["connection"] = 1
        errs["connection_detail"] = f"{type(e).__name__}: {e}"
        sess = None
    keys = _keys(dist, n_ops, key_space, rng, idx, workers)
    if profile == "mixed":
        is_write = rng.random(n_ops) < write_ratio
    else:
        is_write = np.full(n_ops, profile == "write")
    vals = rng.integers(0, 256, (n_ops, value_bytes), dtype=np.uint8)
    barrier.wait()
    if sess is not None:
        for i in range(n_ops):
            k = int(keys[i])
            t0 = time.perf_counter()
            try:
                if is_write[i]:
                    sess.execute_prepared(
                        wq, serialize_params(table, ["key", "v"],
                                             [k, vals[i].tobytes()]))
                else:
                    sess.execute_prepared(
                        rq, serialize_params(table, ["key"], [k]))
                ok += 1
            except DriverError as e:
                kind = _classify(str(e))
                errs[kind] = errs.get(kind, 0) + 1
                continue   # shed ops are near-instant round trips:
                # counting them into lats would inflate ops/s and
                # deflate tail latency exactly when the server sheds
            except Exception as e:   # dead socket mid-run
                errs["connection"] = errs.get("connection", 0) + 1
                errs.setdefault("connection_detail",
                                f"{type(e).__name__}: {e}")
                break
            us = (time.perf_counter() - t0) * 1e6
            lats.append(us)
            hist.update_us(us)
        try:
            sess.close()
        except Exception:
            pass
    results[idx] = (lats, errs, ok)


def run_stress(host: str, port: int, *, profile: str = "mixed",
               connections: int = 16, ops: int = 4096,
               dist: str = "uniform", key_space: int = 4096,
               value_bytes: int = 64, write_ratio: float = 0.5,
               seed: int = 1, setup: bool = True) -> dict:
    """Drive `ops` total operations over `connections` concurrent wire
    connections; returns ops/s + exact p50/p99 + the decaying-histogram
    summary + error counts by class."""
    from cassandra_tpu.client import Cluster
    from cassandra_tpu.service.metrics import LatencyHistogram
    if setup:
        s = Cluster(host, port).connect()
        for ddl in DDL:
            s.execute(ddl)
        s.close()
    per_conn = max(1, ops // connections)
    hist = LatencyHistogram()
    barrier = threading.Barrier(connections + 1)
    results: list = [None] * connections
    threads = [threading.Thread(
        target=_worker, daemon=True,
        args=(i, host, port, profile, per_conn, dist, key_space,
              value_bytes, write_ratio, seed, connections, hist,
              barrier, results))
        for i in range(connections)]
    for t in threads:
        t.start()
    barrier.wait()               # all sessions connected and prepared
    t0 = time.perf_counter()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t0
    lats: list = []
    errors: dict = {}
    ok = 0
    for r in results:
        if r is None:
            errors["connection"] = errors.get("connection", 0) + 1
            continue
        w_lats, w_errs, w_ok = r
        lats += w_lats
        ok += w_ok
        for k, v in w_errs.items():
            if k == "connection_detail":
                errors.setdefault(k, v)
            else:
                errors[k] = errors.get(k, 0) + v
    arr = np.array(lats) if lats else np.array([0.0])
    attempted = ok + sum(v for k, v in errors.items()
                         if isinstance(v, int))
    return {
        "profile": profile, "connections": connections,
        "dist": dist, "ops": attempted, "ok": ok,
        "errors": {k: v for k, v in errors.items() if v},
        "wall_s": round(wall, 3),
        # throughput and percentiles cover SERVED ops only: shed
        # requests are near-instant errors and counting them would
        # overstate capacity precisely when the server is shedding
        "ops_s": round(ok / wall, 1) if wall > 0 else 0.0,
        "p50_us": round(float(np.percentile(arr, 50)), 1),
        "p99_us": round(float(np.percentile(arr, 99)), 1),
        "hist": hist.summary(),
    }


# ------------------------------------------------------------- smoke -----

def _server_thread_count(port: int) -> int:
    from cassandra_tpu.transport.server import server_thread_count
    return server_thread_count(port)


def smoke() -> int:
    """Tier-2 drill: deterministic, seconds-long, exit 1 on violation."""
    import shutil
    import tempfile

    from cassandra_tpu.client import Cluster, serialize_params
    from cassandra_tpu.schema import Schema
    from cassandra_tpu.storage.engine import StorageEngine
    from cassandra_tpu.transport import CQLServer

    failures: list[str] = []

    def check(cond: bool, what: str) -> None:
        print(("ok   " if cond else "FAIL ") + what)
        if not cond:
            failures.append(what)

    base = tempfile.mkdtemp(prefix="ctpu-stress-smoke-")
    engine = StorageEngine(os.path.join(base, "d"), Schema(),
                           commitlog_sync="periodic")
    srv = CQLServer(engine)
    table = _client_table()
    try:
        fixed = _server_thread_count(srv.port)
        check(fixed == len(srv.event_loops) + len(srv.dispatcher.threads),
              f"server runs a fixed thread set ({fixed})")

        # 1. concurrent writes land: 8 connections, disjoint sequential
        # key ranges, then every key reads back over a fresh connection
        n_conns, per = 8, 40
        w = run_stress("127.0.0.1", srv.port, profile="write",
                       connections=n_conns, ops=n_conns * per,
                       dist="sequential", value_bytes=32, seed=7)
        check(w["ok"] == n_conns * per and not w["errors"],
              f"8-connection write run clean ({w['ok']} ops)")
        s = Cluster("127.0.0.1", srv.port).connect()
        rq = s.prepare(SELECT)
        missing = sum(
            1 for k in range(n_conns * per)
            if not s.execute_prepared(
                rq, serialize_params(table, ["key"], [k])).rows)
        check(missing == 0, "every written key reads back "
              f"({n_conns * per - missing}/{n_conns * per})")

        # 2. event-loop contract: 64 concurrent connections, no new
        # server threads
        r = run_stress("127.0.0.1", srv.port, profile="read",
                       connections=64, ops=256, dist="uniform",
                       key_space=n_conns * per, seed=8, setup=False)
        check(r["ok"] > 0 and not r["errors"],
              f"64-connection read run clean ({r['ok']} ops)")
        check(_server_thread_count(srv.port) == fixed,
              "thread count unchanged at 64 connections")

        # 3. overload: pinch the permit cap; the server must SHED with
        # OVERLOADED (not queue, not collapse) and stay responsive
        engine.settings.set("native_transport_max_concurrent_requests", 1)
        srv.permits.reset_high_water()
        o = run_stress("127.0.0.1", srv.port, profile="write",
                       connections=16, ops=400, dist="uniform",
                       key_space=512, value_bytes=32, seed=9,
                       setup=False)
        shed = o["errors"].get("overloaded", 0)
        check(shed > 0, f"permit exhaustion sheds OVERLOADED ({shed})")
        check(o["ok"] > 0, f"server keeps serving under overload "
              f"({o['ok']} ok)")
        check(srv.permits.high_water <= 1,
              f"in-flight never exceeded the cap "
              f"(hwm={srv.permits.high_water})")
        engine.settings.set("native_transport_max_concurrent_requests",
                            256)
        probe = s.execute_prepared(
            rq, serialize_params(table, ["key"], [1]))
        check(bool(probe.rows), "server responsive after overload run")

        # 4. per-client rate limiting, hot-reloaded on and off.
        # rate=2: a NEW connection's bucket starts with a 2-token burst
        # — exactly the worker's two PREPAREs — so every subsequent op
        # competes for a 2 ops/s refill and the shed assertion holds
        # unless a trivial SELECT takes 500 ms (vs ~1 ms measured), not
        # latency-tuned like a generous rate would be
        engine.settings.set("native_transport_rate_limit_ops", 2)
        rl = run_stress("127.0.0.1", srv.port, profile="read",
                        connections=1, ops=60,
                        dist="uniform", key_space=n_conns * per,
                        seed=10, setup=False)
        check(rl["errors"].get("rate_limited", 0) > 0,
              f"rate limiter sheds "
              f"({rl['errors'].get('rate_limited', 0)} of "
              f"{rl['ops']})")
        engine.settings.set("native_transport_rate_limit_ops", 0)
        rl2 = run_stress("127.0.0.1", srv.port, profile="read",
                         connections=1, ops=60, dist="uniform",
                         key_space=n_conns * per, seed=11, setup=False)
        check(not rl2["errors"],
              "rate limit hot-reloads off (clean run)")
        s.close()
    finally:
        srv.close()
        engine.close()
        shutil.rmtree(base, ignore_errors=True)
    if failures:
        print(f"\nsmoke FAILED: {len(failures)} violation(s)")
        return 1
    print("\nsmoke OK")
    return 0


# -------------------------------------------------------------- CLI ------

def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="stress")
    p.add_argument("--profile", choices=("write", "read", "mixed"),
                   default="mixed")
    p.add_argument("--connections", type=int, default=16)
    p.add_argument("--ops", type=int, default=4096)
    p.add_argument("--dist", choices=("uniform", "zipf", "sequential"),
                   default="uniform")
    p.add_argument("--key-space", type=int, default=4096)
    p.add_argument("--value-bytes", type=int, default=64)
    p.add_argument("--write-ratio", type=float, default=0.5)
    p.add_argument("--seed", type=int, default=1)
    p.add_argument("--host", default=None,
                   help="drive an EXISTING server (with --port); "
                        "default spins one up in-process")
    p.add_argument("--port", type=int, default=9042)
    p.add_argument("--smoke", action="store_true",
                   help="tier-2 drill: deterministic seconds-long "
                        "correctness + overload + rate-limit checks")
    args = p.parse_args(argv)
    if args.smoke:
        return smoke()

    srv = engine = None
    base = None
    if args.host is None:
        import shutil
        import tempfile

        from cassandra_tpu.schema import Schema
        from cassandra_tpu.storage.engine import StorageEngine
        from cassandra_tpu.transport import CQLServer
        base = tempfile.mkdtemp(prefix="ctpu-stress-")
        engine = StorageEngine(os.path.join(base, "d"), Schema(),
                               commitlog_sync="periodic")
        srv = CQLServer(engine)
        host, port = "127.0.0.1", srv.port
    else:
        host, port = args.host, args.port
    try:
        if args.profile == "read":     # preload the key space
            run_stress(host, port, profile="write",
                       connections=min(8, args.connections),
                       ops=args.key_space, dist="sequential",
                       value_bytes=args.value_bytes, seed=args.seed)
        out = run_stress(host, port, profile=args.profile,
                         connections=args.connections, ops=args.ops,
                         dist=args.dist, key_space=args.key_space,
                         value_bytes=args.value_bytes,
                         write_ratio=args.write_ratio, seed=args.seed)
        print(json.dumps(out))
    finally:
        if srv is not None:
            srv.close()
            engine.close()
            import shutil
            shutil.rmtree(base, ignore_errors=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
