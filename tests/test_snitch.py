"""Snitch breadth (locator/ SPI): GossipingPropertyFileSnitch,
PropertyFileSnitch, Ec2Snitch az parsing, DynamicEndpointSnitch scores,
and the daemon wiring that feeds NTS placement."""
import pytest

from cassandra_tpu.cluster import snitch as snitch_mod


def test_gpfs_reads_rackdc(tmp_path):
    p = tmp_path / "cassandra-rackdc.properties"
    p.write_text("# comment\ndc=DC_EAST\nrack=RACK9\nprefer_local=true\n")
    s = snitch_mod.GossipingPropertyFileSnitch(str(p))
    assert s.local_dc_rack() == ("DC_EAST", "RACK9")


def test_property_file_snitch(tmp_path):
    p = tmp_path / "cassandra-topology.properties"
    p.write_text("node1=DC1:r1\nnode2=DC2:r7\ndefault=DC9:rX\n")
    s = snitch_mod.PropertyFileSnitch(str(p))
    assert s.dc_rack_of("node1") == ("DC1", "r1")
    assert s.dc_rack_of("node2") == ("DC2", "r7")
    assert s.dc_rack_of("unknown") == ("DC9", "rX")


def test_ec2_snitch_az_parsing():
    assert snitch_mod.Ec2Snitch.parse_az("us-east-1a") == \
        ("us-east-1", "1a")
    assert snitch_mod.Ec2Snitch.parse_az("eu-west-2b") == \
        ("eu-west-2", "2b")
    assert snitch_mod.Ec2Snitch.parse_az("ap-southeast-11c") == \
        ("ap-southeast-11", "11c")
    s = snitch_mod.Ec2Snitch(fetch=lambda: "us-west-2c")
    assert s.local_dc_rack() == ("us-west-2", "2c")


def test_ec2_snitch_file_fetch(tmp_path, monkeypatch):
    az = tmp_path / "az"
    az.write_text("eu-central-1b\n")
    monkeypatch.setenv("CTPU_EC2_AZ_FILE", str(az))
    assert snitch_mod.Ec2Snitch().local_dc_rack() == \
        ("eu-central-1", "1b")


def test_create_from_daemon_config(tmp_path):
    assert isinstance(snitch_mod.create(None), snitch_mod.SimpleSnitch)
    p = tmp_path / "rackdc"
    p.write_text("dc=D\nrack=R\n")
    s = snitch_mod.create({"class": "GossipingPropertyFileSnitch",
                           "rackdc": str(p)})
    assert s.local_dc_rack() == ("D", "R")
    with pytest.raises(ValueError):
        snitch_mod.create({"class": "NopeSnitch"})


def test_snitch_feeds_nts_placement(tmp_path):
    """A GPFS-resolved dc flows into the Endpoint and from there into
    NetworkTopologyStrategy placement — the snitch genuinely decides
    where replicas go."""
    from cassandra_tpu.tools import noded
    rackdc = tmp_path / "rackdc"
    rackdc.write_text("dc=dc_snitched\nrack=rz\n")
    cfg = {"name": "n1", "port": 0, "tokens": [0],
           "data_dir": str(tmp_path / "d"),
           "snitch": {"class": "GossipingPropertyFileSnitch",
                      "rackdc": str(rackdc)}}
    node, transport = noded.build_node(cfg)
    try:
        assert node.endpoint.dc == "dc_snitched"
        assert node.endpoint.rack == "rz"
        s = node.session()
        s.execute("CREATE KEYSPACE ks WITH replication = "
                  "{'class': 'NetworkTopologyStrategy', "
                  "'dc_snitched': 1}")
        s.execute("CREATE TABLE ks.t (k int PRIMARY KEY)")
        s.execute("INSERT INTO ks.t (k) VALUES (1)")
        assert s.execute("SELECT k FROM ks.t").rows == [(1,)]
    finally:
        node.shutdown()
        shut = getattr(transport, "shutdown", None)
        if shut:
            shut()


def test_property_file_snitch_resolves_local_node(tmp_path):
    """Regression: the daemon must pass ITS OWN name to the snitch —
    a nameless lookup silently fell back to the topology default."""
    from cassandra_tpu.tools import noded
    topo = tmp_path / "topo"
    topo.write_text("n1=DC_FROM_FILE:R3\ndefault=dc1:rack1\n")
    cfg = {"name": "n1", "port": 0, "tokens": [0],
           "data_dir": str(tmp_path / "d"),
           "snitch": {"class": "PropertyFileSnitch",
                      "topology": str(topo)}}
    node, transport = noded.build_node(cfg)
    try:
        assert node.endpoint.dc == "DC_FROM_FILE"
        assert node.endpoint.rack == "R3"
    finally:
        node.shutdown()
