"""Query paging: bounded-memory full scans with resumable page state.

Reference counterpart: service/pager/QueryPagers.java +
PartitionRangeQueryPager (page state = last partition key + last
clustering), AggregationQueryPager (aggregates consume pages internally).

The pager walks the token space window by window (each window = the next
`window_parts` partition tokens, discovered from the partition
directories without reading data), merges each window across
memtable + sstables, and yields assembled rows. A page break can land
INSIDE a partition: the state records (token, pk, last clustering frame)
and resumption skips rows at-or-before that position.
"""
from __future__ import annotations

from dataclasses import dataclass

from ..utils import varint as vi
from .cellbatch import pk_lane_key
from .rows import rows_from_batch

MIN_TOKEN = -(1 << 63)


@dataclass(frozen=True)
class PagingState:
    """Position of the LAST row already returned, plus the counters that
    must survive page boundaries: the user LIMIT remaining after this
    page (reference pagers decrement the user limit in the state) and
    how many rows of the current partition were already returned (PER
    PARTITION LIMIT continuity)."""
    token: int
    pk: bytes
    ck: bytes            # serialized clustering frame ('' for static)
    remaining: int = -1  # user-LIMIT rows still owed; -1 = no limit
    ppl_seen: int = 0    # rows of `pk` already returned

    def serialize(self) -> bytes:
        out = bytearray()
        vi.write_signed_vint(self.token, out)
        vi.write_unsigned_vint(len(self.pk), out)
        out += self.pk
        vi.write_unsigned_vint(len(self.ck), out)
        out += self.ck
        vi.write_signed_vint(self.remaining, out)
        vi.write_unsigned_vint(self.ppl_seen, out)
        return bytes(out)

    @classmethod
    def deserialize(cls, data: bytes) -> "PagingState":
        token, pos = vi.read_signed_vint(data, 0)
        n, pos = vi.read_unsigned_vint(data, pos)
        pk = bytes(data[pos:pos + n])
        pos += n
        n, pos = vi.read_unsigned_vint(data, pos)
        ck = bytes(data[pos:pos + n])
        pos += n
        remaining, pos = vi.read_signed_vint(data, pos)
        ppl_seen, pos = vi.read_unsigned_vint(data, pos)
        return cls(token, pk, ck, remaining, ppl_seen)


def paged_rows(store, table, now: int | None = None,
               state: PagingState | None = None, window_parts: int = 64,
               on_batch=None, limits=None):
    """Yield RowData in token order, starting strictly after `state`.
    `store` provides iter_scan(now, after, window_parts) — the local
    ColumnFamilyStore or the coordinator's distributed store. on_batch
    (optional) observes each raw window batch (guardrail hooks)."""
    after = MIN_TOKEN
    skip_key = None
    if state is not None:
        # resume INSIDE the last partition: restart the window at the
        # position's token (inclusive) and skip rows <= the position
        after = state.token - 1 if state.token > MIN_TOKEN else MIN_TOKEN
        comp = table.clustering_comp
        skip_key = (state.token, pk_lane_key(state.pk),
                    comp(state.ck) if state.ck else b"")
    from ..utils import murmur3, partitioners
    for batch in store.iter_scan(now=now, after=after,
                                 window_parts=window_parts,
                                 limits=limits):
        if on_batch is not None:
            on_batch(batch)
        for row in rows_from_batch(table, batch):
            if skip_key is not None:
                tok = partitioners.token_of(row.pk)
                pos = (tok, pk_lane_key(row.pk),
                       table.clustering_comp(row.ck_frame)
                       if row.ck_frame else b"")
                if pos <= skip_key:
                    continue
                skip_key = None   # storage order: everything after passes
            yield row


def position_of(table, row, remaining: int = -1,
                ppl_seen: int = 0) -> PagingState:
    """PagingState pointing AT this row (resume returns rows after it)."""
    from ..utils import murmur3, partitioners
    return PagingState(partitioners.token_of(row.pk), row.pk,
                       row.ck_frame,
                       remaining, ppl_seen)
