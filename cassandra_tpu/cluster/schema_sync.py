"""Distributed schema agreement — the Paxos-backed epoch log (TCM).

Reference counterpart: tcm/ClusterMetadata.java:81 + the log-based
transformation model (every metadata change is an ordered log entry;
replicas converge by applying the same entries in the same order),
committed through a Paxos-backed processor on a CMS replica group
(tcm/PaxosBackedProcessor.java:57, tcm/Commit.java). Scaled to this
framework: the replicated unit is the DDL STATEMENT TEXT (or a
#topology transformation), ordered by a per-cluster epoch counter.

Commit model (cluster/cms.py): every epoch slot is decided by
single-decree Paxos over the CMS replica set (the min(3) lowest-named
endpoints). A CMS member coordinates directly; any other node forwards
(SCHEMA_FORWARD) to a live CMS member and applies the acked entry, so
the statement is visible locally when execute() returns. A minority
partition CANNOT commit (MetadataUnavailable) — no fork is possible;
a proposer that loses a slot to a concurrent commit applies the winner
and retries its own statement at the next slot.

  - Learn paths: CMS members apply at Paxos-commit time; all peers get
    SCHEMA_PUSH(epoch, entry); a node seeing a future epoch pulls the
    gap (SCHEMA_PULL, async — the response callback runs on the same
    dispatch thread later; nothing here may block on a response).
  - A (re)starting node replays its persisted log, then pulls anything
    newer from the first live peer.
  - Same-epoch conflicts cannot be produced by CMS commits; the
    deterministic winner rule below survives only as tolerance for
    logs predating the CMS (and screams into stderr if it ever fires).

Enabled for per-process schemas (TCP deployments and per-node-schema
test rigs); LocalCluster shares one Schema object in-process and needs
no sync.
"""
from __future__ import annotations

import json
import os
import sys
import threading
import time

from .messaging import Verb


DDL_STATEMENTS = {
    "CreateKeyspaceStatement", "CreateTableStatement",
    "CreateIndexStatement", "CreateTypeStatement", "CreateViewStatement",
    "CreateFunctionStatement", "CreateAggregateStatement",
    "CreateTriggerStatement", "DropTriggerStatement",
    "DropStatement", "AlterTableStatement",
    # NOT TruncateStatement: truncation is a DATA operation with its own
    # cluster fan-out (TRUNCATE_REQ); replaying it from the schema log on
    # a late-joining node would wipe rows written after the original
}


class SchemaForwardError(ValueError):
    """The designated coordinator rejected the DDL (e.g. parse or
    execution error there) — surfaced to the issuing session."""


TOPOLOGY_PREFIX = "#topology "


def apply_topology_to_ring(ring, extra: dict) -> None:
    """Apply one topology transformation to a Ring. The single
    definition both the epoch-log path (TCP clusters) and the shared-ring
    path (LocalCluster) go through — reference
    tcm/transformations/* applied to ClusterMetadata's tokenMap."""
    from .ring import Endpoint

    op = extra["op"]
    nd = extra.get("node") or {}
    ep = Endpoint(nd["name"], nd.get("dc", "dc1"), nd.get("rack", "rack1"),
                  nd.get("host", "127.0.0.1"), int(nd.get("port", 0)))

    def existing(name: str):
        for e in ring.endpoints:
            if e.name == name:
                return e
        raise ValueError(f"endpoint {name} not in ring")

    tokens = [int(t) for t in extra.get("tokens") or []]
    if op == "register":
        ring.add_node(ep, tokens)
    elif op == "start_join":
        ring.add_pending(ep, tokens)
    elif op == "finish_join":
        ring.promote_pending(ep)
    elif op == "abort_join":
        ring.cancel_pending(ep)
    elif op == "leave":
        ring.remove_node(existing(nd["name"]))
    elif op == "start_move":
        ring.start_move(existing(nd["name"]), tokens)
    elif op == "finish_move":
        ring.finish_move(existing(nd["name"]),
                         [int(t) for t in extra["old_tokens"]])
    elif op == "abort_move":
        ring.abort_move(existing(nd["name"]))
    elif op == "start_replace":
        ring.start_replace(ep, existing(extra["target"]))
    elif op == "finish_replace":
        ring.finish_replace(ep)
    elif op == "abort_replace":
        ring.cancel_replace(ep)
    else:
        raise ValueError(f"unknown topology op {op!r}")


def emit_topology_event(node, extra: dict) -> None:
    """Driver-facing TOPOLOGY_CHANGE for a committed transformation
    (transport Event.TopologyChange role). Only the COMMIT points of
    multi-step sequences emit — drivers see the ownership flip, not the
    intermediate pending states."""
    op = extra["op"]
    nd = extra.get("node") or {}
    info = {"host": nd.get("host", "127.0.0.1"),
            "port": int(nd.get("port", 0))}
    change = {"register": "NEW_NODE", "finish_join": "NEW_NODE",
              "finish_replace": "NEW_NODE", "leave": "REMOVED_NODE",
              "finish_move": "MOVED_NODE"}.get(op)
    if change is None:
        return
    emit = getattr(node, "emit_event", None)
    if emit is not None:
        emit("TOPOLOGY_CHANGE", {"change": change, **info})


class SchemaSync:
    FORWARD_TIMEOUT = 5.0
    # pulls re-fetch a window of already-seen epochs so a conflict
    # winner whose one-way push was lost still reconciles on the next
    # pull (startup catch-up or any gap pull) via the winner rule
    PULL_OVERLAP = 8

    def __init__(self, node, directory: str):
        self.node = node
        os.makedirs(directory, exist_ok=True)
        self.path = os.path.join(directory, "schema_log.jsonl")
        self.epoch = 0
        self._lock = threading.RLock()
        self._load()
        # epoch -> exception raised applying that entry locally; the
        # coordinator pops its own slot to surface the error to the
        # client (commit-then-apply: application happens after the
        # Paxos decision, so errors can no longer surface from a
        # pre-commit local execution). Bounded — see _apply_entry.
        self._apply_errors: dict[int, Exception] = {}
        from .cms import CMSService
        self.cms = CMSService(node, self, directory)
        ms = node.messaging
        ms.register_handler(Verb.SCHEMA_PUSH, self._handle_push)
        ms.register_handler(Verb.SCHEMA_PULL, self._handle_pull)
        ms.register_handler(Verb.SCHEMA_FORWARD, self._handle_forward)
        # epoch anti-entropy (tcm PeerLogFetcher role): the epoch rides
        # gossip app-state; a node seeing a peer ahead pulls the gap —
        # so a straggler that missed a push AND had its one gap-pull
        # time out still converges within a gossip round
        self._pulling = False
        g = getattr(node, "gossiper", None)
        if g is not None:
            g.on_app_state = self._on_peer_app_state
            self._publish_epoch()

    # ------------------------------------------------------------- log --

    def _load(self) -> None:
        # the file is durability; _entries (epoch -> LAST record at that
        # epoch, i.e. the conflict winner) is the read path — handlers
        # consult it under _lock, so lookups must not re-read the file
        self._entries: dict[int, tuple] = {}
        if not os.path.exists(self.path):
            return
        with open(self.path) as f:
            for line in f:
                try:
                    rec = json.loads(line)
                except ValueError:
                    break               # torn tail
                e = int(rec["epoch"])
                self._entries[e] = (e, rec["query"], rec.get("keyspace"),
                                    rec.get("extra") or {},
                                    rec.get("coord"))
                self.epoch = max(self.epoch, e)

    def _append(self, epoch: int, query: str, keyspace, extra,
                coord: str | None = None) -> None:
        coord = coord or self.node.endpoint.name
        with open(self.path, "a") as f:
            f.write(json.dumps({"epoch": epoch, "query": query,
                                "keyspace": keyspace, "extra": extra,
                                "coord": coord}) + "\n")
            f.flush()
            os.fsync(f.fileno())
        self._entries[epoch] = (epoch, query, keyspace, extra or {},
                                coord)

    def _publish_epoch(self) -> None:
        """Advertise the applied epoch in gossip app-state (catch-up
        signal for _on_peer_app_state on peers)."""
        g = getattr(self.node, "gossiper", None)
        if g is None:
            return
        with g._lock:
            g.states[g.ep].app_states["schema_epoch"] = self.epoch

    def _on_peer_app_state(self, ep, apps: dict) -> None:
        """Gossip says `ep` has applied a newer epoch than ours: pull
        the gap on a worker thread (this callback runs on the dispatch
        thread and must not block). One pull in flight at a time."""
        pe = apps.get("schema_epoch")
        if pe is None or int(pe) <= self.epoch or self._pulling:
            return
        self._pulling = True

        def run():
            try:
                self.pull_from_peers(timeout=5.0, prefer=ep)
            finally:
                self._pulling = False

        threading.Thread(target=run, daemon=True,
                         name="schema-antientropy").start()

    def entries_after(self, epoch: int) -> list[tuple]:
        """Entries newer than `epoch`, ONE record per epoch: an epoch
        rewritten by conflict resolution keeps only its LAST (winning)
        record, so pullers apply exactly what push-path nodes applied."""
        with self._lock:
            return [self._entries[e] for e in sorted(self._entries)
                    if e > epoch]

    def _entry_at(self, epoch: int):
        """Last (i.e. winning) record logged at `epoch`, or None."""
        return self._entries.get(epoch)

    def entry_at(self, epoch: int):
        """Thread-safe committed-entry lookup (CMS prepare fast path)."""
        with self._lock:
            return self._entries.get(epoch)

    def learn(self, slot: int, ddict: dict) -> None:
        """Apply a Paxos-DECIDED entry if it is next in sequence.
        COMMIT-THEN-APPLY: this is the ONLY place CMS-committed entries
        execute, for the proposer and replicas alike — nothing runs
        locally before the decision (reference
        tcm/ClusterMetadataService.java commit-then-apply). A stale
        slot is a no-op; a gap is left for push/pull catch-up (the
        decided value will arrive again there)."""
        with self._lock:
            if slot != self.epoch + 1:
                return
            self._apply_entry(slot, ddict["q"], ddict["k"],
                              ddict.get("x") or {},
                              coord=ddict.get("c"))

    # ------------------------------------------------- CMS membership --

    def cms_members(self) -> list:
        """CMS replica set as-of THIS node's applied log prefix."""
        with self._lock:
            return self._cms_members_locked()

    def _cms_members_locked(self) -> list:
        """The min(CMS_SIZE) lowest-named FULLY-JOINED endpoints of the
        log-materialized ring. Pending joiners/replacements are NOT
        eligible until their finish_join/finish_replace entry commits,
        so the set changes only at a committed log entry and the OLD
        set decides the slot that admits the newcomer — the reference's
        explicit logged CMS reconfiguration (tcm/membership/, the old
        set votes the handover). Caller holds _lock (ring mutations
        happen under it, via _apply_entry)."""
        from .cms import CMS_SIZE
        eps = sorted(self.node.ring.endpoints, key=lambda e: e.name)
        if not eps:
            return [self.node.endpoint]
        return eps[:CMS_SIZE]

    def snapshot_for_commit(self) -> tuple:
        """(next slot, CMS member set) captured atomically under the
        log lock: slot N is ALWAYS decided by the member set the log
        prefix N-1 materializes. Two proposers of the same slot hold
        the same prefix, hence the same set — their quorums intersect
        even across a membership change (the non-intersecting-quorum
        hazard of reading the live ring mid-flight)."""
        with self._lock:
            return self.epoch + 1, self._cms_members_locked()

    # ------------------------------------------------------- application --

    def _apply_local(self, query: str, keyspace, extra: dict) -> None:
        """Execute the DDL against the local node WITHOUT re-entering
        the coordination path. Object ids the coordinator assigned ride
        in `extra` so every node agrees (mutations route by table id)."""
        if query.startswith(TOPOLOGY_PREFIX):
            apply_topology_to_ring(self.node.ring, extra)
            emit_topology_event(self.node, extra)
            return
        from ..cql.parser import parse
        from ..cql.execution import Executor
        stmt = parse(query)
        tid = extra.get("table_id")
        if tid is not None:
            name = type(stmt).__name__
            if name == "CreateTableStatement":
                stmt.options = dict(stmt.options or {})
                stmt.options["id"] = tid
            elif name == "CreateViewStatement":
                stmt.view_id = tid
        # NODE-LOCAL application: replayed entries must never re-enter
        # any distributed fan-out path
        Executor(self.node.engine).execute(stmt, keyspace=keyspace)

    def _preassign_extra(self, stmt, keyspace) -> dict:
        """Object ids assigned BEFORE the Paxos commit, so the decided
        entry carries them and every node — including the coordinator,
        which applies only after the decision — creates the object with
        the same id (mutations route by table id). Reference: tcm
        transformations carry the ids they assign."""
        if stmt is None:
            return {}
        name = type(stmt).__name__
        if name not in ("CreateTableStatement", "CreateViewStatement"):
            return {}
        ks = stmt.keyspace or keyspace
        try:
            # IF NOT EXISTS over an existing object keeps its id
            return {"table_id":
                    str(self.node.schema.get_table(ks, stmt.name).id)}
        except Exception:
            pass
        if name == "CreateTableStatement" and "id" in (stmt.options or {}):
            return {"table_id": str(stmt.options["id"])}
        import uuid
        return {"table_id": str(uuid.uuid4())}

    def _validate_ddl(self, stmt, keyspace) -> None:
        """Semantic pre-checks run BEFORE the Paxos commit. Under
        commit-then-apply nothing executes locally until the slot is
        decided, so errors the old flow surfaced from its pre-commit
        local execution must be caught here or they would pollute the
        committed log. Mirrors the _exec_* guard prefixes
        (cql/execution.py) for the common cases; anything subtler
        surfaces from the post-commit application — deterministically,
        on every node — via _apply_errors."""
        if stmt is None:
            return
        from ..cql.execution import InvalidRequest
        schema = self.node.schema
        name = type(stmt).__name__
        if name == "CreateKeyspaceStatement":
            if stmt.name in schema.keyspaces and not stmt.if_not_exists:
                raise InvalidRequest(f"keyspace {stmt.name} exists")
        elif name == "CreateTableStatement":
            ks = stmt.keyspace or keyspace
            if ks is None:
                raise InvalidRequest("no keyspace for CREATE TABLE")
            if ks not in schema.keyspaces:
                raise InvalidRequest(f"unknown keyspace {ks}")
            if stmt.name in schema.keyspaces[ks].tables \
                    and not stmt.if_not_exists:
                raise InvalidRequest(f"table {ks}.{stmt.name} exists")
            if not stmt.partition_key:
                raise InvalidRequest("missing PRIMARY KEY")
        elif name == "CreateViewStatement":
            ks = stmt.keyspace or keyspace
            bks = stmt.base_keyspace or keyspace
            if ks is None or bks is None:
                raise InvalidRequest(
                    "no keyspace for CREATE MATERIALIZED VIEW")
            if (ks, stmt.name) in getattr(schema, "views", {}) \
                    and not stmt.if_not_exists:
                raise InvalidRequest(f"view {ks}.{stmt.name} exists")
            try:
                schema.get_table(bks, stmt.base_table)
            except KeyError as e:
                raise InvalidRequest(str(e))
        elif name == "AlterTableStatement":
            ks = stmt.keyspace or keyspace
            if ks is None:
                raise InvalidRequest("no keyspace specified")
            try:
                schema.get_table(ks, stmt.name)
            except KeyError as e:
                raise InvalidRequest(str(e))
        elif name == "CreateIndexStatement":
            ks = stmt.keyspace or keyspace
            if ks is None:
                raise InvalidRequest("no keyspace specified")
            try:
                schema.get_table(ks, stmt.table)
            except KeyError as e:
                raise InvalidRequest(str(e))
        elif name == "DropStatement" and not stmt.if_exists:
            ks = stmt.keyspace or keyspace
            if stmt.what == "keyspace":
                if stmt.name not in schema.keyspaces:
                    raise InvalidRequest(f"unknown keyspace {stmt.name}")
            elif stmt.what == "table" and ks is not None:
                try:
                    schema.get_table(ks, stmt.name)
                except KeyError as e:
                    raise InvalidRequest(str(e))

    # ----------------------------------------------------- coordination --

    def coordinate(self, query: str, keyspace, stmt,
                   extra_override: dict | None = None):
        """Entry point from the CQL processor. Runs on a client/session
        thread (never the messaging dispatch thread), so it MAY block
        on responses. A CMS member commits through Paxos directly; any
        other node forwards to a live CMS member and applies the acked
        entry. NO local-commit fallback exists: if no CMS quorum is
        reachable the statement FAILS (MetadataUnavailable) — a
        minority partition must not fork the log."""
        from .cms import MetadataUnavailable
        members = self.cms.members()
        if self.node.endpoint in members:
            return self._coordinate_cms(query, keyspace, stmt,
                                        extra_override)
        pre_epoch = self.epoch
        targets = [m for m in members if self.node.is_alive(m)]
        if not targets:
            raise MetadataUnavailable(
                f"no CMS member reachable "
                f"({[m.name for m in members]} all down)")
        ambiguous = False
        for des in targets:
            ack = self._forward(des, query, keyspace, extra_override)
            if ack is None:
                ambiguous = True
                continue     # this member unreachable: try the next
            epoch, extra = ack
            with self._lock:
                behind = epoch > self.epoch + 1
            if behind:
                # missed entries: the CMS member has them all (it just
                # committed `epoch`). Pull OUTSIDE the lock: the
                # response is processed on the dispatch thread, and
                # _on_pull_response needs this same lock — a pull
                # under the lock would deadlock-till-timeout and stall
                # every message on the node.
                self.pull_from_peers(timeout=self.FORWARD_TIMEOUT,
                                     prefer=des)
            with self._lock:
                if epoch == self.epoch + 1:
                    self._apply_entry(epoch, query, keyspace,
                                      extra or {}, coord=des.name)
                if self.epoch < epoch:
                    # committed cluster-wide, but this node could not
                    # catch up (peers unreachable mid-pull) — surface
                    # that rather than return success for a table this
                    # node does not have yet
                    raise SchemaForwardError(
                        f"DDL committed at epoch {epoch} but local "
                        f"catch-up failed (local epoch "
                        f"{self.epoch}); retry")
            from ..cql.execution import ResultSet
            return ResultSet([], [])   # DDL result shape
        if ambiguous:
            # a forward may have committed with only the ack lost.
            # Re-issuing a committed CREATE would fork its table id —
            # pull first; if our exact statement now appears, it
            # committed: done.
            self.pull_from_peers(timeout=self.FORWARD_TIMEOUT)
            if any(rec[1] == query
                   for rec in self.entries_after(pre_epoch)):
                from ..cql.execution import ResultSet
                return ResultSet([], [])
        raise MetadataUnavailable(
            f"no CMS member answered the DDL forward "
            f"({[m.name for m in members]})")

    def _coordinate_cms(self, query: str, keyspace, stmt,
                        extra_override: dict | None):
        """CMS-member commit — COMMIT-THEN-APPLY (reference
        tcm/ClusterMetadataService.java: transformations apply only
        after the log commit). The statement is validated and its
        object ids assigned up front, but NOTHING executes locally
        until the Paxos decision: local application happens as this
        node's own COMMIT self-delivery inside commit_entry
        (cms._handle_commit -> learn). A member dying mid-round
        therefore strands no locally-applied residue. A liveness
        quorum check fails fast so a minority-side statement is
        refused before any Paxos traffic."""
        from .cms import MetadataUnavailable
        _slot, members = self.snapshot_for_commit()
        need = len(members) // 2 + 1
        live = [m for m in members
                if m == self.node.endpoint or self.node.is_alive(m)]
        if len(live) < need:
            raise MetadataUnavailable(
                f"metadata commit needs {need}/{len(members)} CMS "
                f"members ({[m.name for m in members]}), "
                f"{len(live)} reachable")
        self._validate_ddl(stmt, keyspace)
        extra = extra_override if extra_override is not None \
            else self._preassign_extra(stmt, keyspace)
        epoch = self.cms.commit_entry(
            query, keyspace, extra,
            revalidate=(None if stmt is None
                        else lambda: self._validate_ddl(stmt, keyspace)))
        with self._lock:
            err = self._apply_errors.pop(epoch, None)
        if err is not None:
            raise err
        from ..cql.execution import ResultSet
        return ResultSet([], [])   # DDL result shape

    def _forward(self, des, query: str, keyspace, extra_override):
        """Send the DDL to the designated node; block for its ack.
        Returns (epoch, extra) on success, None if unreachable; raises
        SchemaForwardError if the designated node rejected the DDL."""
        done = threading.Event()
        box: dict = {}

        def on_rsp(msg):
            box["payload"] = msg.payload
            done.set()

        def on_fail(_msg_id):
            done.set()

        self.node.messaging.send_with_callback(
            Verb.SCHEMA_FORWARD, (query, keyspace, extra_override or {}),
            des, on_response=on_rsp, on_failure=on_fail,
            timeout=self.FORWARD_TIMEOUT)
        if not done.wait(self.FORWARD_TIMEOUT) or "payload" not in box:
            return None
        payload = box["payload"]
        if payload[0] == "err":
            raise SchemaForwardError(
                f"DDL rejected by designated coordinator "
                f"{des.name}: {payload[1]}")
        return int(payload[1]), payload[2] or {}

    # ---------------------------------------------------------- handlers --

    def _handle_forward(self, msg):
        """CMS-member side of a forwarded DDL. The Paxos commit BLOCKS
        on quorum responses, so the work runs on a worker thread and
        the ack is sent asynchronously (messaging.respond) — the
        dispatch thread must stay free to process the very promise/
        accept responses the commit is waiting for."""
        query, keyspace, fwd_extra = msg.payload

        def run():
            from ..cql.parser import parse
            try:
                if not self.cms.is_member():
                    raise SchemaForwardError(
                        f"{self.node.endpoint.name} is not a CMS "
                        f"member")
                extra = fwd_extra or {}
                # commit-then-apply, same as _coordinate_cms: validate
                # + pre-assign ids, commit via Paxos, let the COMMIT
                # self-delivery apply — no pre-decision local residue
                revalidate = None
                if not query.startswith(TOPOLOGY_PREFIX):
                    stmt = parse(query)
                    self._validate_ddl(stmt, keyspace)
                    if not extra:
                        extra = self._preassign_extra(stmt, keyspace)
                    revalidate = \
                        lambda: self._validate_ddl(stmt, keyspace)
                epoch = self.cms.commit_entry(query, keyspace, extra,
                                              revalidate=revalidate)
                with self._lock:
                    err = self._apply_errors.pop(epoch, None)
                if err is not None:
                    raise err
            except Exception as e:
                self.node.messaging.respond(
                    msg, Verb.SCHEMA_FORWARD, ("err", repr(e), None))
                return
            self.node.messaging.respond(
                msg, Verb.SCHEMA_FORWARD, ("ok", epoch, extra))

        threading.Thread(target=run, daemon=True,
                         name="schema-forward").start()
        return None

    def _handle_push(self, msg):
        epoch, query, keyspace, extra = msg.payload
        displaced = None
        with self._lock:
            if epoch == self.epoch + 1:
                self._apply_entry(epoch, query, keyspace, extra or {},
                                  coord=msg.sender.name)
                return None
            if epoch <= self.epoch:
                displaced = self._adopt_winner_locked(
                    epoch, query, keyspace, extra, msg.sender.name)
        if epoch > self.epoch + 1:
            # gap: pull the missing prefix from the sender. Async on
            # purpose — this handler runs on the single dispatch thread,
            # and the pull response can only be processed by that same
            # thread, so blocking here would deadlock the node.
            self.node.messaging.send_with_callback(
                Verb.SCHEMA_PULL,
                max(0, self.epoch - self.PULL_OVERLAP), msg.sender,
                on_response=self._on_pull_response,
                timeout=self.node.proxy.timeout)
        elif displaced is not None:
            self._recoordinate_async(displaced)
        return None

    def _adopt_winner_locked(self, epoch, query, keyspace, extra,
                             coord: str):
        """Same-epoch conflict resolution. With the CMS (cluster/cms.py)
        every epoch is Paxos-decided, so two nodes holding DIFFERENT
        entries at one epoch is impossible for CMS-committed logs —
        this path survives only as tolerance for logs predating the CMS
        and is LOUD when it fires (it would indicate log corruption or
        a mixed-version cluster). The entry whose coordinator has the
        HIGHER name wins deterministically; returns our displaced entry
        (for re-coordination) or None. Caller holds _lock."""
        mine = self._entry_at(epoch)
        if mine is None or mine[1] == query \
                or (coord or "") <= (mine[4] or ""):
            return None
        print(f"[schema-sync] {self.node.endpoint.name}: SAME-EPOCH "
              f"CONFLICT at {epoch} ({mine[1]!r} vs {query!r}) — "
              f"impossible for CMS-committed logs; adopting "
              f"deterministic winner. Investigate log integrity.",
              file=sys.stderr)
        self._apply_entry(epoch, query, keyspace, extra or {},
                          coord=coord)
        return mine

    def _recoordinate_async(self, displaced) -> None:
        """A displaced statement re-coordinates at a fresh epoch,
        keeping its assigned object ids. Runs on a separate thread:
        coordinate() blocks on responses, and callers here are on the
        dispatch thread."""
        _e, q, k, x, _c = displaced

        def run():
            try:
                self.coordinate(q, k, None, extra_override=x)
            except Exception as e:
                # the statement's local side effects exist but it lost
                # its epoch and could not be re-committed — tell the
                # operator to re-issue it instead of losing it silently
                print(f"[schema-sync] {self.node.endpoint.name}: "
                      f"re-coordination of displaced DDL failed "
                      f"({q!r}): {e!r} — re-issue it manually",
                      file=sys.stderr)

        threading.Thread(target=run, daemon=True,
                         name="schema-recoordinate").start()

    def _handle_pull(self, msg):
        after = int(msg.payload)
        return Verb.SCHEMA_PUSH, ("entries", self.entries_after(after))

    def _on_pull_response(self, msg):
        tag, entries = msg.payload
        displaced_all = []
        with self._lock:
            for epoch, query, keyspace, extra, coord in entries:
                if epoch == self.epoch + 1:
                    self._apply_entry(epoch, query, keyspace,
                                      extra or {}, coord=coord)
                elif epoch <= self.epoch:
                    # overlap window: adopt a conflict winner this node
                    # missed (same deterministic rule as _handle_push) —
                    # and our displaced entry re-commits at a fresh
                    # epoch, exactly as if the push had arrived
                    d = self._adopt_winner_locked(epoch, query, keyspace,
                                                  extra, coord)
                    if d is not None:
                        displaced_all.append(d)
        for d in displaced_all:
            self._recoordinate_async(d)

    def _apply_entry(self, epoch: int, query: str, keyspace,
                     extra: dict, coord: str | None = None) -> None:
        """Apply + log a received entry. The coordinator NAME is
        recorded as received (never this node's own), because the
        same-epoch conflict rule compares against it — every node must
        store the same name or different nodes pick different winners."""
        try:
            self._apply_local(query, keyspace, extra)
        except Exception as e:
            # an entry that fails locally (e.g. already-applied effect)
            # still advances the epoch — convergence over strictness,
            # matching pre-TCM schema-merge behaviour. But NOT silently:
            # e.g. CREATE TRIGGER fails on a node missing the trigger
            # file, and the operator must learn this node diverged. The
            # coordinator additionally pops its own slot's error to
            # surface it to the client (commit-then-apply).
            print(f"[schema-sync] {self.node.endpoint.name}: replicated "
                  f"DDL failed locally at epoch {epoch} ({query!r}): "
                  f"{e!r}", file=sys.stderr)
            # bounded, OLDEST-first: a blanket clear() could wipe an
            # in-flight coordinator's error before its pop, acking a
            # failed DDL as success
            while len(self._apply_errors) > 64:
                del self._apply_errors[min(self._apply_errors)]
            self._apply_errors[epoch] = e
        # entry durable + readable BEFORE the epoch advances: any
        # reader observing epoch >= N is guaranteed entries 1..N are
        # present (the fsync can take milliseconds under load — an
        # epoch-first order lets epoch polls race past a missing entry)
        self._append(epoch, query, keyspace, extra, coord=coord)
        self.epoch = max(self.epoch, epoch)
        self._publish_epoch()

    def commit_topology(self, extra: dict) -> None:
        """Commit a topology transformation as an epoch-log entry —
        membership/placement rides the SAME ordered log as DDL (the
        reference's ClusterMetadata holds schema AND tokenMap/placements,
        all changed through one log). The entry text embeds the op so
        the same-epoch conflict rule dedups identical retries."""
        query = TOPOLOGY_PREFIX + json.dumps(extra, sort_keys=True)
        self.coordinate(query, None, None, extra_override=extra)

    def replay_all(self) -> None:
        """Re-apply every logged entry in epoch order (daemon restart).
        The ring is the log's materialization, so topology entries MUST
        replay; DDL that already exists fails benignly (warned)."""
        for e in sorted(self._entries):
            _epoch, query, keyspace, extra, _coord = self._entries[e]
            try:
                self._apply_local(query, keyspace, extra or {})
            except Exception as ex:
                print(f"[schema-sync] {self.node.endpoint.name}: replay "
                      f"of epoch {e} ({query[:60]!r}) failed: {ex!r}",
                      file=sys.stderr)

    def pull_from_peers(self, timeout: float = 5.0, prefer=None,
                        peers=None) -> bool:
        """Catch-up: ask a peer (preferring `prefer`) for newer
        entries, RETRYING within `timeout` until one answers — a node
        that just healed from a partition must converge on its own,
        not wait for external help. Liveness is re-read every attempt,
        and if gossip still convicts every peer (heartbeats lag a heal
        by up to a gossip round) the convicted peers are contacted
        optimistically — a dead one simply doesn't answer. Blocks on
        responses — callers must be off the dispatch thread (startup
        threads, session threads). `peers` overrides discovery — a
        FRESH node joining has an empty ring and only knows its
        configured seed addresses (tcm/Discovery role). Returns True
        if any peer answered (callers that REQUIRE the cluster's log —
        auto-join discovery — must treat False as fatal, not as 'I am
        the first node')."""
        deadline = time.monotonic() + timeout
        while True:
            if peers is not None:
                cand = [ep for ep in peers if ep != self.node.endpoint]
            else:
                ring_eps = [ep for ep in self.node.ring.endpoints
                            if ep != self.node.endpoint]
                live = [ep for ep in ring_eps
                        if self.node.is_alive(ep)]
                cand = live or ring_eps
            if prefer is not None and prefer in cand:
                cand.remove(prefer)
                cand.insert(0, prefer)
            for ep in cand:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
                # bound each attempt so one silent peer can't eat the
                # whole deadline when others might answer
                per_try = remaining if len(cand) == 1 \
                    else min(remaining, max(1.0, timeout / len(cand)))
                done = threading.Event()

                def on_rsp(msg, _done=done):
                    self._on_pull_response(msg)
                    _done.set()

                self.node.messaging.send_with_callback(
                    Verb.SCHEMA_PULL,
                    max(0, self.epoch - self.PULL_OVERLAP), ep,
                    on_response=on_rsp, timeout=per_try)
                if done.wait(per_try):
                    return True
            if deadline - time.monotonic() <= 0.05:
                return False
            time.sleep(0.05)
