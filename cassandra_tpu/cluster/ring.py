"""Token ring: node ownership of the murmur3 token space.

Reference counterpart: dht/IPartitioner + Murmur3Partitioner (tokens),
locator/TokenMetadata (ring state; superseded by tcm/ClusterMetadata's
tokenMap in 5.1 — our Ring plays that tokenMap role), dht/Splitter
(even range splitting).
"""
from __future__ import annotations

import bisect
from dataclasses import dataclass, field

from ..utils import murmur3


@dataclass(frozen=True)
class Endpoint:
    """A node address (InetAddressAndPort role). host/port address real
    socket transports; the in-process transport routes by identity."""
    name: str
    dc: str = "dc1"
    rack: str = "rack1"
    host: str = "127.0.0.1"
    port: int = 0

    def __repr__(self):
        return self.name


class Ring:
    """token -> owning endpoint, sorted; replica walks go clockwise
    (locator/AbstractReplicationStrategy.calculateNaturalReplicas walk)."""

    def __init__(self):
        self._tokens: list[int] = []
        self._owners: dict[int, Endpoint] = {}
        self.endpoints: dict[Endpoint, list[int]] = {}
        self.pending: dict[Endpoint, list[int]] = {}
        # replacement in progress: new endpoint -> dead endpoint whose
        # tokens it will assume (tcm/sequences replace-address flow).
        # Writes meanwhile go to BOTH (future ring maps dead -> new).
        self.replacing: dict[Endpoint, Endpoint] = {}
        # token move in progress: endpoint -> the OLD tokens it will
        # release at finish_move. The future ring excludes them, so
        # writes racing the move are duplicated to the owners gaining
        # the surrendered ranges (not just the gained ones).
        self.moving: dict[Endpoint, list[int]] = {}
        self._future_cache: "Ring | None" = None

    def add_node(self, ep: Endpoint, tokens: list[int]) -> None:
        for t in tokens:
            if t in self._owners:
                raise ValueError(f"token {t} already owned")
            bisect.insort(self._tokens, t)
            self._owners[t] = ep
        self.endpoints.setdefault(ep, []).extend(tokens)
        self._future_cache = None

    def remove_node(self, ep: Endpoint) -> None:
        for t in self.endpoints.pop(ep, []):
            self._tokens.remove(t)
            del self._owners[t]
        self._future_cache = None

    def remove_tokens(self, ep: Endpoint, tokens: list[int]) -> None:
        """Release a subset of ep's tokens (the shrink half of a token
        move; tcm/sequences/Move releases the old placement last)."""
        owned = self.endpoints.get(ep, [])
        for t in tokens:
            if self._owners.get(t) == ep:
                self._tokens.remove(t)
                del self._owners[t]
                owned.remove(t)
        if ep in self.endpoints and not self.endpoints[ep]:
            del self.endpoints[ep]
        self._future_cache = None

    # -------------------------------------------------------------- move --

    def start_move(self, ep: Endpoint, new_tokens: list[int]) -> None:
        """Begin a token move: new tokens pending, old tokens marked
        moving (excluded from the future ring so racing writes reach the
        owners gaining the surrendered ranges)."""
        self.add_pending(ep, new_tokens)
        self.moving[ep] = list(self.endpoints.get(ep, []))
        self._future_cache = None

    def finish_move(self, ep: Endpoint, old_tokens: list[int]) -> None:
        self.promote_pending(ep)
        self.remove_tokens(ep, old_tokens)
        self.moving.pop(ep, None)
        self._future_cache = None

    def abort_move(self, ep: Endpoint) -> None:
        self.cancel_pending(ep)
        self.moving.pop(ep, None)
        self._future_cache = None

    # ------------------------------------------------------- replacement --

    def start_replace(self, new_ep: Endpoint, dead_ep: Endpoint) -> None:
        """Begin replace-address: new_ep will assume dead_ep's tokens.
        Until finish, reads still route to the (dead) owner's replica set
        and writes are duplicated to new_ep via the future ring."""
        if dead_ep not in self.endpoints:
            raise ValueError(f"{dead_ep} not in ring")
        if new_ep in self.endpoints or new_ep in self.replacing:
            raise ValueError(f"{new_ep} already joined or replacing")
        self.replacing[new_ep] = dead_ep
        self._future_cache = None

    def finish_replace(self, new_ep: Endpoint) -> None:
        """Commit point: dead node leaves, new node owns its tokens."""
        dead = self.replacing.pop(new_ep)
        toks = list(self.endpoints.get(dead, []))
        self.remove_node(dead)
        self.add_node(new_ep, toks)
        self._future_cache = None

    def cancel_replace(self, new_ep: Endpoint) -> None:
        self.replacing.pop(new_ep, None)
        self._future_cache = None

    def successors(self, token: int):
        """Endpoints in ring order starting at the first token >= token."""
        if not self._tokens:
            return
        start = bisect.bisect_left(self._tokens, token)
        n = len(self._tokens)
        for i in range(n):
            t = self._tokens[(start + i) % n]
            yield self._owners[t]

    def primary(self, token: int) -> Endpoint:
        return next(self.successors(token))

    def token_of(self, key: bytes) -> int:
        from ..utils import partitioners
        return partitioners.token_of(key)

    def ranges_of(self, ep: Endpoint) -> list[tuple[int, int]]:
        """(start, end] ranges owned primarily by ep."""
        out = []
        n = len(self._tokens)
        for i, t in enumerate(self._tokens):
            if self._owners[t] is ep or self._owners[t] == ep:
                prev = self._tokens[(i - 1) % n]
                out.append((prev, t))
        return out

    def clone_without(self, ep: Endpoint) -> "Ring":
        """A copy of the ring as it was before `ep` joined (bootstrap
        stream sources must be computed against PRE-join ownership)."""
        r = Ring()
        for e, toks in self.endpoints.items():
            if e != ep:
                r.add_node(e, list(toks))
        return r

    # --------------------------------------------------- pending ranges --
    # A joining node's tokens are PENDING until its bootstrap stream
    # completes: reads keep routing to the pre-join owners, while writes
    # are duplicated to the pending node so nothing written mid-join is
    # missing when ownership flips (locator/ReplicaPlans pending
    # replicas; tcm/sequences/BootstrapAndJoin write-survey phase).

    def add_pending(self, ep: Endpoint, tokens: list[int]) -> None:
        taken = set(self._owners)
        for toks in self.pending.values():
            taken.update(toks)
        for t in tokens:
            if t in taken:
                raise ValueError(f"token {t} already owned or pending")
        self.pending[ep] = list(tokens)
        self._future_cache = None

    def promote_pending(self, ep: Endpoint) -> None:
        """Atomically flip ownership to the joined node (the join commit
        point: reads start routing to it, write duplication stops)."""
        toks = self.pending.pop(ep)
        self._future_cache = None
        self.add_node(ep, toks)

    def cancel_pending(self, ep: Endpoint) -> None:
        self.pending.pop(ep, None)
        self._future_cache = None

    def future_ring(self) -> "Ring":
        """The ring as it will be once every pending join/replace
        completes — pending-write placement is computed against this
        (cached: every write during a join consults it)."""
        if self._future_cache is not None:
            return self._future_cache
        r = Ring()
        swap = {dead: new for new, dead in self.replacing.items()}
        for e, toks in self.endpoints.items():
            drop = set(self.moving.get(e, ()))
            kept = [t for t in toks if t not in drop]
            if kept:
                r.add_node(swap.get(e, e), kept)
        for e, toks in self.pending.items():
            r.add_node(e, list(toks))
        self._future_cache = r
        return r

    def all_ranges(self) -> list[tuple[int, int]]:
        """Every (start, end] vnode range of the ring (start > end for the
        wrap-around range)."""
        n = len(self._tokens)
        return [(self._tokens[(i - 1) % n], t)
                for i, t in enumerate(self._tokens)]


def allocate_tokens(ring: "Ring", vnodes: int = 4) -> list[int]:
    """Tokens for a JOINING node: bisect the current largest ranges so
    ownership stays balanced as the cluster grows (the
    dht/tokenallocator role — the reference optimizes per-RF ownership
    variance; bisection of the widest arcs is the core of it)."""
    MIN, MAX = -(1 << 63) + 1, (1 << 63) - 1
    existing = sorted(ring._owners)
    for toks in ring.pending.values():
        existing.extend(toks)
    existing.sort()
    if not existing:
        span = (1 << 64) // vnodes
        return [MIN + i * span for i in range(vnodes)]
    out: list[int] = []
    for _ in range(vnodes):
        pts = sorted(existing + out)
        best_gap, best_mid = -1, None
        n = len(pts)
        for i, t in enumerate(pts):
            prev = pts[(i - 1) % n]
            gap = (t - prev) % (1 << 64)
            if gap == 0:
                gap = 1 << 64        # single token: the arc IS the ring
            mid = prev + gap // 2
            if mid > MAX:
                mid -= 1 << 64
            if gap > best_gap and mid not in pts:
                best_gap, best_mid = gap, int(mid)
        if best_mid is None:         # pathological density: fall back
            import random
            while True:
                c = random.randrange(MIN, MAX)
                if c not in pts:
                    best_mid = c
                    break
        out.append(best_mid)
    return out


def even_tokens(n_nodes: int, vnodes: int = 1) -> list[list[int]]:
    """Evenly spread initial tokens (dht/tokenallocator role, simplified
    to the uniform case)."""
    total = n_nodes * vnodes
    span = 1 << 64
    step = span // total
    out: list[list[int]] = [[] for _ in range(n_nodes)]
    for i in range(total):
        tok = -(1 << 63) + 1 + i * step
        out[i % n_nodes].append(tok)
    return out
