"""User-defined functions and aggregates.

Reference counterpart: cql3/functions/ (UDFunction.java — sandboxed
java/javascript bodies — and UDAggregate.java). The sandbox problem is
solved differently here: function bodies are written in a deliberately
tiny EXPRESSION language (LANGUAGE expr) evaluated over a strict Python
AST whitelist — arithmetic, comparisons, boolean logic, conditionals and
a fixed builtin set over the declared arguments. No attribute access, no
imports, no subscripts, no statements: the evaluator cannot reach
anything beyond its arguments, which is the property the reference's
sandbox exists to enforce.

    CREATE FUNCTION ks.double_it (x int) RETURNS int
        LANGUAGE expr AS 'x * 2';
    CREATE AGGREGATE ks.my_sum (int) SFUNC plus STYPE int INITCOND 0;
"""
from __future__ import annotations

import ast as py_ast
import operator as _op_mod
from dataclasses import dataclass

_ALLOWED_NODES = (
    py_ast.Expression, py_ast.BinOp, py_ast.UnaryOp, py_ast.BoolOp,
    py_ast.Compare, py_ast.IfExp, py_ast.Call, py_ast.Name,
    py_ast.Constant, py_ast.Load,
    # NOTE: Pow is deliberately absent — '9**9**9**9' would pin the CPU
    # before any result-size check could run (the reference sandbox uses
    # execution timeouts for this; an allowlist without ** is simpler)
    py_ast.Add, py_ast.Sub, py_ast.Mult, py_ast.Div, py_ast.FloorDiv,
    py_ast.Mod, py_ast.USub, py_ast.UAdd, py_ast.Not,
    py_ast.And, py_ast.Or, py_ast.Eq, py_ast.NotEq, py_ast.Lt,
    py_ast.LtE, py_ast.Gt, py_ast.GtE,
)

class FunctionError(ValueError):
    pass


# Result-size cap for any single evaluation step. Without it the
# allowlist still permits unbounded MEMORY amplification ('x * 10**9'
# with a string x allocates gigabytes in one op, before any post-hoc
# check could run), so every BinOp is rewritten to route through
# _guarded_binop which estimates the result size from the operands
# BEFORE executing the op.
_MAX_RESULT_BYTES = 1 << 20


def _approx_size(x) -> int:
    if isinstance(x, (str, bytes, bytearray)):
        return len(x)
    if isinstance(x, (list, tuple)):
        return 16 * len(x)      # per-element slot cost, contents uncounted
    if isinstance(x, int):
        return x.bit_length() >> 3
    return 8


_BINOPS = {
    "Add": _op_mod.add, "Sub": _op_mod.sub, "Mult": _op_mod.mul,
    "Div": _op_mod.truediv, "FloorDiv": _op_mod.floordiv,
    "Mod": _op_mod.mod,
}


def _guarded_binop(op: str, a, b):
    # list/tuple included: row values hand UDFs real Python lists, and
    # list * int amplifies exactly like str * int
    seq = (str, bytes, bytearray, list, tuple)
    if op == "Mult":
        if isinstance(a, int) and isinstance(b, seq):
            est = max(a, 0) * max(_approx_size(b), 1)
        elif isinstance(b, int) and isinstance(a, seq):
            est = max(b, 0) * max(_approx_size(a), 1)
        else:
            est = _approx_size(a) + _approx_size(b)
    elif op == "Mod" and isinstance(a, seq):
        # '%0999999999d' % x pads to a width the operand sizes don't
        # reveal — printf-style formatting is simply not allowed
        raise FunctionError("string formatting (%) not allowed in UDFs")
    else:
        est = _approx_size(a) + _approx_size(b) + 1
    if est > _MAX_RESULT_BYTES:
        raise FunctionError(
            f"expression result too large (~{est} bytes > "
            f"{_MAX_RESULT_BYTES} cap)")
    return _BINOPS[op](a, b)


def _guarded_concat(*xs):
    parts = [str(x) for x in xs]
    if sum(map(len, parts)) > _MAX_RESULT_BYTES:
        raise FunctionError("concat result too large")
    return "".join(parts)


_BUILTINS = {
    "abs": abs, "min": min, "max": max, "len": len, "round": round,
    "int": int, "float": float, "str": str,
    "upper": lambda s: s.upper(), "lower": lambda s: s.lower(),
    "concat": _guarded_concat,
}


class _GuardBinOps(py_ast.NodeTransformer):
    """Rewrite `a <op> b` to `__binop__('<Op>', a, b)` AFTER the
    allowlist check (the injected name never appears in user source)."""

    def visit_BinOp(self, node):
        self.generic_visit(node)
        return py_ast.copy_location(
            py_ast.Call(
                func=py_ast.Name(id="__binop__", ctx=py_ast.Load()),
                args=[py_ast.Constant(type(node.op).__name__),
                      node.left, node.right],
                keywords=[]),
            node)


def compile_expression(body: str, arg_names: list[str]):
    """Parse + whitelist-check the expression once; returns a callable.
    Anything outside the allowlist (attributes, subscripts, lambdas,
    comprehensions, walrus, f-strings, imports...) is rejected at
    CREATE time."""
    if "__binop__" in arg_names:
        raise FunctionError("'__binop__' is a reserved argument name")
    try:
        tree = py_ast.parse(body, mode="eval")
    except SyntaxError as e:
        raise FunctionError(f"bad expression: {e}")
    for node in py_ast.walk(tree):
        if not isinstance(node, _ALLOWED_NODES):
            raise FunctionError(
                f"disallowed construct {type(node).__name__} in function "
                "body (LANGUAGE expr allows arithmetic, comparisons, "
                "boolean logic, conditionals and the builtin set)")
        if isinstance(node, py_ast.Call):
            if not isinstance(node.func, py_ast.Name) \
                    or node.func.id not in _BUILTINS:
                raise FunctionError(
                    f"unknown function call in body "
                    f"(allowed: {sorted(_BUILTINS)})")
            if node.keywords:
                raise FunctionError("keyword arguments not allowed")
        if isinstance(node, py_ast.Name) and node.id not in arg_names \
                and node.id not in _BUILTINS:
            raise FunctionError(f"unknown name {node.id!r} in body")
    tree = py_ast.fix_missing_locations(_GuardBinOps().visit(tree))
    code = compile(tree, "<udf>", "eval")

    def call(args: list):
        scope = dict(_BUILTINS)
        scope.update(zip(arg_names, args))
        # after the args: an argument named __binop__ must not shadow
        # the guard every binary op routes through
        scope["__binop__"] = _guarded_binop
        try:
            return eval(code, {"__builtins__": {}}, scope)
        except FunctionError:
            raise
        except Exception as e:
            raise FunctionError(f"function evaluation failed: {e}")
    return call


@dataclass
class UDF:
    keyspace: str
    name: str
    arg_names: list
    arg_types: list          # type strings (repr of CQLType)
    returns: str
    body: str

    def __post_init__(self):
        self._call = compile_expression(self.body, list(self.arg_names))

    def __call__(self, args: list):
        if any(a is None for a in args):
            return None      # RETURNS NULL ON NULL INPUT semantics
        return self._call(args)


@dataclass
class UDA:
    keyspace: str
    name: str
    arg_type: str
    sfunc: str               # state UDF name: (state, value) -> state
    stype: str
    finalfunc: str | None
    initcond: object

    def aggregate(self, registry, values: list):
        sf = registry.get_function(self.keyspace, self.sfunc)
        if sf is None:
            raise FunctionError(f"unknown SFUNC {self.sfunc}")
        state = self.initcond
        for v in values:
            if v is None:
                continue
            state = sf._call([state, v])
        if self.finalfunc:
            ff = registry.get_function(self.keyspace, self.finalfunc)
            if ff is None:
                raise FunctionError(f"unknown FINALFUNC {self.finalfunc}")
            state = ff._call([state])
        return state


class FunctionRegistry:
    def __init__(self):
        self.functions: dict[tuple, UDF] = {}
        self.aggregates: dict[tuple, UDA] = {}

    def add_function(self, f: UDF, replace: bool = False) -> None:
        key = (f.keyspace, f.name)
        if key in self.functions and not replace:
            raise FunctionError(f"function {f.name} exists")
        self.functions[key] = f

    def add_aggregate(self, a: UDA, replace: bool = False) -> None:
        key = (a.keyspace, a.name)
        if key in self.aggregates and not replace:
            raise FunctionError(f"aggregate {a.name} exists")
        self.aggregates[key] = a

    def get_function(self, keyspace: str, name: str) -> UDF | None:
        return self.functions.get((keyspace, name))

    def get_aggregate(self, keyspace: str, name: str) -> UDA | None:
        return self.aggregates.get((keyspace, name))

    def drop(self, keyspace: str, name: str,
             kind: str | None = None) -> None:
        """kind 'function'/'aggregate' scopes the drop — DROP AGGREGATE
        must never delete a scalar function sharing the name."""
        key = (keyspace, name)
        if kind in (None, "function") and key in self.functions:
            del self.functions[key]
        elif kind in (None, "aggregate") and key in self.aggregates:
            del self.aggregates[key]
        else:
            raise KeyError(name)

    # ------------------------------------------------------------ serde --

    def to_list(self) -> list[dict]:
        out = []
        for f in self.functions.values():
            out.append({"kind": "function", "keyspace": f.keyspace,
                        "name": f.name, "arg_names": list(f.arg_names),
                        "arg_types": list(f.arg_types),
                        "returns": f.returns, "body": f.body})
        for a in self.aggregates.values():
            out.append({"kind": "aggregate", "keyspace": a.keyspace,
                        "name": a.name, "arg_type": a.arg_type,
                        "sfunc": a.sfunc, "stype": a.stype,
                        "finalfunc": a.finalfunc,
                        "initcond": a.initcond})
        return out

    def load_list(self, items: list[dict]) -> None:
        for d in items:
            try:
                if d["kind"] == "function":
                    self.add_function(UDF(
                        d["keyspace"], d["name"], d["arg_names"],
                        d["arg_types"], d["returns"], d["body"]),
                        replace=True)
                else:
                    self.add_aggregate(UDA(
                        d["keyspace"], d["name"], d["arg_type"],
                        d["sfunc"], d["stype"], d.get("finalfunc"),
                        d.get("initcond")), replace=True)
            except FunctionError:
                pass   # a body the current allowlist rejects is dropped
