"""TCP internode transport: real sockets behind the LocalTransport seam.

Reference counterpart: net/MessagingService.java:208 (outbound connection
pool per peer), net/HandshakeProtocol.java (magic + version + sender
identification before frames flow), net/FrameEncoder/FrameDecoderCrc
(length-prefixed CRC-protected frames).

Protocol:
  handshake: [8B magic b"CTPUNET1"][u32 crc of sender-endpoint blob]
             [u32 len][sender endpoint blob (wire codec)]
  frames:    [u32 len][u32 crc32(body)][body = wire-encoded message]

Failure model: a send to an unreachable/broken peer drops the frame and
tears down the cached connection — callers' callback timeouts drive
retries/hints exactly as with dropped packets. With a TLSConfig, every
internode connection is mutual TLS against the cluster CA (reference
server_encryption_options); without one, inbound connections are
accepted from anyone who completes the handshake (trusted network).
"""
from __future__ import annotations

import socket
import struct
import threading
import zlib

from . import wire
from .messaging import MessageFilters
from .ring import Endpoint

_MAGIC = b"CTPUNET1"
_MAX_FRAME = 256 << 20


class _Conn:
    def __init__(self, sock: socket.socket):
        self.sock = sock
        self.lock = threading.Lock()

    def send_frame(self, body: bytes) -> None:
        hdr = struct.pack("<II", len(body), zlib.crc32(body))
        with self.lock:
            self.sock.sendall(hdr + body)

    def close(self) -> None:
        try:
            self.sock.close()
        except OSError:
            pass


def _read_exact(sock: socket.socket, n: int) -> bytes | None:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            return None
        buf += chunk
    return bytes(buf)


def _read_frame(sock: socket.socket) -> bytes | None:
    hdr = _read_exact(sock, 8)
    if hdr is None:
        return None
    length, crc = struct.unpack("<II", hdr)
    if length > _MAX_FRAME:
        raise ValueError("frame too large")
    body = _read_exact(sock, length)
    if body is None or zlib.crc32(body) != crc:
        return None
    return body


class TcpTransport:
    """Socket transport for ONE node's MessagingService. register() binds
    the listen socket at the endpoint's (host, port); deliver() sends
    through a per-peer pooled connection, dialing on demand."""

    def __init__(self, tls=None):
        """tls: a cluster.tls.TLSConfig — when set, every internode
        connection is mutual TLS against the cluster CA (reference
        server_encryption_options internode_encryption: all); plaintext
        dials are rejected at handshake."""
        self.filters = MessageFilters()
        self._svc = None
        self._listen: socket.socket | None = None
        self._out: dict[Endpoint, _Conn] = {}
        self._lock = threading.Lock()
        self._closed = False
        # decode/deliver bugs that cost a serving thread its connection
        # (the broad guard in _serve_conn): counted so a silent
        # connect/drop loop is visible, not invisible
        self.serve_failures = 0
        self.tls = tls
        self._srv_ctx = tls.server_context() if tls else None
        self._cli_ctx = tls.client_context() if tls else None

    # ---------------------------------------------------------- lifecycle --

    def register(self, ep: Endpoint, svc) -> None:
        if self._svc is not None:
            raise RuntimeError("TcpTransport hosts exactly one node")
        self._svc = svc
        self._ep = ep
        s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        s.bind((ep.host, ep.port))
        s.listen(64)
        if ep.port == 0:
            # kernel-assigned port: callers read it back via bound_port
            self.bound_port = s.getsockname()[1]
        else:
            self.bound_port = ep.port
        self._listen = s
        t = threading.Thread(target=self._accept_loop, daemon=True,
                             name=f"tcp-accept-{ep.name}")
        t.start()

    def unregister(self, ep: Endpoint) -> None:
        self._closed = True
        if self._listen is not None:
            try:
                self._listen.close()
            except OSError:
                pass
        with self._lock:
            conns = list(self._out.values())
            self._out.clear()
        for c in conns:
            c.close()

    # ------------------------------------------------------------ outbound --

    def deliver(self, msg) -> None:
        if self.filters.should_drop(msg):
            return
        body = wire.encode_message(msg)
        conn = self._connection(msg.to)
        if conn is None:
            return          # unreachable: timeouts drive the failure path
        try:
            conn.send_frame(body)
        except OSError:
            with self._lock:
                if self._out.get(msg.to) is conn:
                    del self._out[msg.to]
            conn.close()

    def _connection(self, to: Endpoint) -> _Conn | None:
        with self._lock:
            conn = self._out.get(to)
        if conn is not None:
            return conn
        try:
            sock = socket.create_connection((to.host, to.port), timeout=2.0)
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            if self._cli_ctx is not None:
                import ssl
                try:
                    sock = self._cli_ctx.wrap_socket(sock)
                except (ssl.SSLError, OSError):
                    sock.close()
                    return None
            blob = bytearray()
            wire._enc(self._ep, blob)
            sock.sendall(_MAGIC + struct.pack("<II", zlib.crc32(bytes(blob)),
                                              len(blob)) + bytes(blob))
        except OSError:
            return None
        conn = _Conn(sock)
        with self._lock:
            existing = self._out.get(to)
            if existing is not None:
                conn.close()
                return existing
            self._out[to] = conn
        return conn

    # ------------------------------------------------------------- inbound --

    def _accept_loop(self) -> None:
        while not self._closed:
            try:
                sock, _addr = self._listen.accept()
            except OSError:
                return
            try:
                threading.Thread(target=self._serve_conn, args=(sock,),
                                 daemon=True).start()
            except Exception:
                # thread-limit exhaustion under a connection burst must
                # drop THIS connection, not end the accept loop — the
                # peer retries; a dead accept loop partitions the node
                # silently (ctpulint worker-loops)
                try:
                    sock.close()
                except OSError:
                    pass

    def _serve_conn(self, sock: socket.socket) -> None:
        if self._srv_ctx is not None:
            import ssl
            try:
                sock = self._srv_ctx.wrap_socket(sock, server_side=True)
            except (ssl.SSLError, OSError):
                # plaintext or untrusted-cert dial: refuse silently
                try:
                    sock.close()
                except OSError:
                    pass
                return
        try:
            magic = _read_exact(sock, len(_MAGIC))
            if magic != _MAGIC:
                sock.close()
                return
            hdr = _read_exact(sock, 8)
            if hdr is None:
                sock.close()
                return
            crc, length = struct.unpack("<II", hdr)
            if length > 65536:   # handshake blob is one Endpoint
                sock.close()
                return
            blob = _read_exact(sock, length)
            if blob is None or zlib.crc32(blob) != crc:
                sock.close()
                return
            wire._dec(blob, 0)   # sender endpoint (identification only)
            while not self._closed:
                body = _read_frame(sock)
                if body is None:
                    return
                try:
                    msg = wire.decode_message(body)
                except (ValueError, IndexError, KeyError, TypeError,
                        struct.error):
                    continue     # malformed frame: drop, keep the conn
                if self.filters.should_drop(msg):
                    continue
                svc = self._svc
                if svc is not None and not svc.closed:
                    svc.inbound(msg)
        except OSError:
            pass   # normal socket teardown: peer reset, EOF mid-frame
        except Exception:
            # a decode/deliver BUG also ends only this peer's
            # connection (the finally closes it; the peer reconnects) —
            # but unlike routine socket errors it is counted, so a
            # silent connect/drop loop shows up in the transport stats
            # instead of wedging invisibly (ctpulint worker-loops)
            self.serve_failures += 1
        finally:
            try:
                sock.close()
            except OSError:
                pass
