"""CQL end-to-end tests — the CQLTester equivalent (reference:
test/unit/org/apache/cassandra/cql3/CQLTester.java pattern: an embedded
single node driven through real CQL)."""
import time
import uuid

import pytest

from cassandra_tpu.cql import Session
from cassandra_tpu.cql.execution import InvalidRequest
from cassandra_tpu.schema import Schema
from cassandra_tpu.storage.engine import StorageEngine


@pytest.fixture
def session(tmp_path):
    eng = StorageEngine(str(tmp_path / "data"), Schema(),
                        commitlog_sync="batch")
    s = Session(eng)
    s.execute("CREATE KEYSPACE ks WITH replication = "
              "{'class': 'SimpleStrategy', 'replication_factor': 1}")
    s.execute("USE ks")
    yield s
    eng.close()


def test_create_insert_select(session):
    session.execute("""CREATE TABLE users (
        id int, seq int, name text, age int,
        PRIMARY KEY (id, seq))""")
    session.execute("INSERT INTO users (id, seq, name, age) "
                    "VALUES (1, 1, 'alice', 30)")
    session.execute("INSERT INTO users (id, seq, name) VALUES (1, 2, 'bob')")
    rs = session.execute("SELECT * FROM users WHERE id = 1")
    assert rs.dicts() == [
        {"id": 1, "seq": 1, "name": "alice", "age": 30},
        {"id": 1, "seq": 2, "name": "bob", "age": None}]
    rs = session.execute("SELECT name FROM users WHERE id = 1 AND seq = 2")
    assert rs.rows == [("bob",)]
    assert session.execute("SELECT * FROM users WHERE id = 99").rows == []


def test_types_roundtrip(session):
    session.execute("""CREATE TABLE t (
        id uuid PRIMARY KEY, a bigint, b double, c boolean, d blob,
        e timestamp, f varint, g decimal, h inet)""")
    u = uuid.uuid4()
    session.execute(
        "INSERT INTO t (id, a, b, c, d, f, h) VALUES "
        f"({u}, 9223372036854775807, 1.5, true, 0xdeadbeef, "
        "123456789012345678901234567890, '10.1.2.3')")
    row = session.execute(f"SELECT * FROM t WHERE id = {u}").dicts()[0]
    assert row["a"] == 9223372036854775807
    assert row["b"] == 1.5
    assert row["c"] is True
    assert row["d"] == bytes.fromhex("deadbeef")
    assert row["f"] == 123456789012345678901234567890
    assert row["h"] == "10.1.2.3"


def test_bind_markers(session):
    session.execute("CREATE TABLE kv (k int PRIMARY KEY, v text)")
    qid = session.prepare("INSERT INTO kv (k, v) VALUES (?, ?)")
    for i in range(10):
        session.execute_prepared(qid, (i, f"v{i}"))
    rs = session.execute("SELECT v FROM kv WHERE k = ?", (7,))
    assert rs.rows == [("v7",)]


def test_update_and_delete(session):
    session.execute("CREATE TABLE kv (k int, c int, v text, "
                    "PRIMARY KEY (k, c))")
    session.execute("INSERT INTO kv (k, c, v) VALUES (1, 1, 'a')")
    session.execute("INSERT INTO kv (k, c, v) VALUES (1, 2, 'b')")
    session.execute("UPDATE kv SET v = 'A' WHERE k = 1 AND c = 1")
    assert session.execute(
        "SELECT v FROM kv WHERE k = 1 AND c = 1").rows == [("A",)]
    # cell delete
    session.execute("DELETE v FROM kv WHERE k = 1 AND c = 1")
    row = session.execute("SELECT * FROM kv WHERE k = 1 AND c = 1").dicts()
    assert row and row[0]["v"] is None  # row survives (liveness)
    # row delete
    session.execute("DELETE FROM kv WHERE k = 1 AND c = 2")
    assert session.execute(
        "SELECT * FROM kv WHERE k = 1 AND c = 2").rows == []
    # partition delete
    session.execute("INSERT INTO kv (k, c, v) VALUES (2, 1, 'x')")
    session.execute("DELETE FROM kv WHERE k = 2")
    assert session.execute("SELECT * FROM kv WHERE k = 2").rows == []


def test_update_without_insert_leaves_no_row_marker(session):
    # reference semantics: UPDATE creates cells but no liveness; deleting
    # the cell removes the row entirely
    session.execute("CREATE TABLE kv (k int, c int, v text, "
                    "PRIMARY KEY (k, c))")
    session.execute("UPDATE kv SET v = 'x' WHERE k = 1 AND c = 1")
    assert len(session.execute("SELECT * FROM kv WHERE k = 1").rows) == 1
    session.execute("DELETE v FROM kv WHERE k = 1 AND c = 1")
    assert session.execute("SELECT * FROM kv WHERE k = 1").rows == []


def test_collections(session):
    session.execute("""CREATE TABLE prefs (
        id int PRIMARY KEY, tags map<text, text>, names set<text>,
        items list<int>)""")
    session.execute("INSERT INTO prefs (id, tags, names, items) VALUES "
                    "(1, {'a': 'x', 'b': 'y'}, {'n1', 'n2'}, [3, 1, 2])")
    row = session.execute("SELECT * FROM prefs WHERE id = 1").dicts()[0]
    assert row["tags"] == {"a": "x", "b": "y"}
    assert row["names"] == {"n1", "n2"}
    assert row["items"] == [3, 1, 2]
    # element ops
    session.execute("UPDATE prefs SET tags['c'] = 'z' WHERE id = 1")
    session.execute("UPDATE prefs SET names = names + {'n3'} WHERE id = 1")
    session.execute("UPDATE prefs SET names = names - {'n1'} WHERE id = 1")
    session.execute("UPDATE prefs SET items = items + [4] WHERE id = 1")
    row = session.execute("SELECT * FROM prefs WHERE id = 1").dicts()[0]
    assert row["tags"] == {"a": "x", "b": "y", "c": "z"}
    assert row["names"] == {"n2", "n3"}
    assert row["items"] == [3, 1, 2, 4]
    # full overwrite
    session.execute("UPDATE prefs SET tags = {'only': 'one'} WHERE id = 1")
    row = session.execute("SELECT tags FROM prefs WHERE id = 1").dicts()[0]
    assert row["tags"] == {"only": "one"}
    # delete one key
    session.execute("DELETE tags['only'] FROM prefs WHERE id = 1")
    row = session.execute("SELECT tags FROM prefs WHERE id = 1").dicts()[0]
    assert row["tags"] is None


def test_ttl(session):
    session.execute("CREATE TABLE kv (k int PRIMARY KEY, v text)")
    session.execute("INSERT INTO kv (k, v) VALUES (1, 'x') USING TTL 1")
    assert session.execute("SELECT * FROM kv WHERE k = 1").rows
    time.sleep(1.2)
    assert session.execute("SELECT * FROM kv WHERE k = 1").rows == []


def test_using_timestamp_lww(session):
    session.execute("CREATE TABLE kv (k int PRIMARY KEY, v text)")
    session.execute("INSERT INTO kv (k, v) VALUES (1, 'new') "
                    "USING TIMESTAMP 2000")
    session.execute("INSERT INTO kv (k, v) VALUES (1, 'old') "
                    "USING TIMESTAMP 1000")
    assert session.execute("SELECT v FROM kv WHERE k = 1").rows == [("new",)]


def test_batch(session):
    session.execute("CREATE TABLE kv (k int, c int, v text, "
                    "PRIMARY KEY (k, c))")
    session.execute("""BEGIN BATCH
        INSERT INTO kv (k, c, v) VALUES (1, 1, 'a');
        INSERT INTO kv (k, c, v) VALUES (1, 2, 'b');
        UPDATE kv SET v = 'c' WHERE k = 1 AND c = 3;
        APPLY BATCH""")
    assert len(session.execute("SELECT * FROM kv WHERE k = 1").rows) == 3


def test_in_order_limit(session):
    session.execute("CREATE TABLE ts (k int, c int, v int, "
                    "PRIMARY KEY (k, c)) WITH CLUSTERING ORDER BY (c DESC)")
    for c in range(10):
        session.execute(f"INSERT INTO ts (k, c, v) VALUES (1, {c}, {c * 10})")
    rs = session.execute("SELECT c FROM ts WHERE k = 1 LIMIT 3")
    assert [r[0] for r in rs.rows] == [9, 8, 7]       # DESC storage order
    rs = session.execute("SELECT c FROM ts WHERE k = 1 ORDER BY c ASC LIMIT 3")
    assert [r[0] for r in rs.rows] == [0, 1, 2]
    rs = session.execute("SELECT c FROM ts WHERE k = 1 AND c IN (2, 5)")
    assert sorted(r[0] for r in rs.rows) == [2, 5]
    rs = session.execute("SELECT c FROM ts WHERE k = 1 AND c >= 7")
    assert sorted(r[0] for r in rs.rows) == [7, 8, 9]


def test_allow_filtering_and_aggregates(session):
    session.execute("CREATE TABLE e (k int, c int, v int, "
                    "PRIMARY KEY (k, c))")
    for k in range(3):
        for c in range(4):
            session.execute(
                f"INSERT INTO e (k, c, v) VALUES ({k}, {c}, {k * 100 + c})")
    with pytest.raises(Exception):
        session.execute("SELECT * FROM e WHERE v = 102")
    rs = session.execute("SELECT * FROM e WHERE v = 102 ALLOW FILTERING")
    assert rs.dicts() == [{"k": 1, "c": 2, "v": 102}]
    assert session.execute("SELECT count(*) FROM e").rows == [(12,)]
    rs = session.execute("SELECT min(v), max(v), sum(v), avg(v) FROM e "
                         "WHERE k = 1")
    assert rs.rows == [(100, 103, 406, 101.5)]


def test_static_columns(session):
    session.execute("CREATE TABLE s (k int, c int, st text static, v int, "
                    "PRIMARY KEY (k, c))")
    session.execute("INSERT INTO s (k, st) VALUES (1, 'shared')")
    session.execute("INSERT INTO s (k, c, v) VALUES (1, 1, 10)")
    session.execute("INSERT INTO s (k, c, v) VALUES (1, 2, 20)")
    rows = session.execute("SELECT * FROM s WHERE k = 1").dicts()
    assert len(rows) == 2
    assert all(r["st"] == "shared" for r in rows)


def test_lwt_single_node(session):
    session.execute("CREATE TABLE kv (k int PRIMARY KEY, v text)")
    rs = session.execute("INSERT INTO kv (k, v) VALUES (1, 'a') "
                         "IF NOT EXISTS")
    assert rs.rows[0][0] is True
    rs = session.execute("INSERT INTO kv (k, v) VALUES (1, 'b') "
                         "IF NOT EXISTS")
    assert rs.rows[0][0] is False
    assert session.execute("SELECT v FROM kv WHERE k = 1").rows == [("a",)]
    rs = session.execute("UPDATE kv SET v = 'c' WHERE k = 1 IF v = 'a'")
    assert rs.rows[0][0] is True
    rs = session.execute("UPDATE kv SET v = 'd' WHERE k = 1 IF v = 'wrong'")
    assert rs.rows[0][0] is False
    assert session.execute("SELECT v FROM kv WHERE k = 1").rows == [("c",)]


def test_ddl_alter_drop_truncate(session):
    session.execute("CREATE TABLE t1 (k int PRIMARY KEY, v int)")
    session.execute("ALTER TABLE t1 ADD extra text")
    session.execute("INSERT INTO t1 (k, v, extra) VALUES (1, 2, 'e')")
    assert session.execute("SELECT extra FROM t1 WHERE k = 1").rows == [("e",)]
    session.execute("ALTER TABLE t1 DROP extra")
    with pytest.raises(Exception):
        session.execute("SELECT extra FROM t1 WHERE k = 1")
    session.execute("TRUNCATE t1")
    assert session.execute("SELECT * FROM t1").rows == []
    session.execute("DROP TABLE t1")
    with pytest.raises(Exception):
        session.execute("SELECT * FROM t1")
    session.execute("DROP TABLE IF EXISTS t1")  # no error
    session.execute("CREATE TABLE IF NOT EXISTS t1 (k int PRIMARY KEY)")
    session.execute("CREATE TABLE IF NOT EXISTS t1 (k int PRIMARY KEY)")


def test_udt_and_tuple_vector(session):
    session.execute("CREATE TYPE addr (street text, zip int)")
    session.execute("CREATE TABLE u (k int PRIMARY KEY, a frozen<addr>, "
                    "tp tuple<int, text>, vec vector<float, 3>)")
    session.execute("INSERT INTO u (k, tp) VALUES (1, (5, 'five'))")
    row = session.execute("SELECT tp FROM u WHERE k = 1").dicts()[0]
    assert row["tp"] == (5, "five")


def test_composite_partition_key(session):
    session.execute("CREATE TABLE cp (a int, b int, c int, v text, "
                    "PRIMARY KEY ((a, b), c))")
    session.execute("INSERT INTO cp (a, b, c, v) VALUES (1, 2, 3, 'x')")
    rs = session.execute("SELECT v FROM cp WHERE a = 1 AND b = 2")
    assert rs.rows == [("x",)]
    with pytest.raises(Exception):
        session.execute("SELECT * FROM cp WHERE a = 1")  # incomplete pk


def test_survives_flush_and_restart(tmp_path):
    eng = StorageEngine(str(tmp_path / "d"), Schema(), commitlog_sync="batch")
    s = Session(eng)
    s.execute("CREATE KEYSPACE ks WITH replication = "
              "{'class': 'SimpleStrategy', 'replication_factor': 1}")
    s.execute("USE ks")
    s.execute("CREATE TABLE kv (k int PRIMARY KEY, v text)")
    for i in range(20):
        s.execute(f"INSERT INTO kv (k, v) VALUES ({i}, 'v{i}')")
    eng.store("ks", "kv").flush()
    for i in range(20, 30):
        s.execute(f"INSERT INTO kv (k, v) VALUES ({i}, 'v{i}')")
    assert len(s.execute("SELECT * FROM kv").rows) == 30
    eng.close()


def test_counters(session):
    session.execute("CREATE TABLE cnt (k int PRIMARY KEY, hits counter)")
    for _ in range(5):
        session.execute("UPDATE cnt SET hits = hits + 3 WHERE k = 1")
    session.execute("UPDATE cnt SET hits = hits - 5 WHERE k = 1")
    assert session.execute("SELECT hits FROM cnt WHERE k = 1").rows == [(10,)]


def test_secondary_index(session):
    session.execute("CREATE TABLE users2 (id int PRIMARY KEY, email text, "
                    "age int)")
    session.execute("CREATE INDEX ON users2 (email)")
    for i in range(20):
        session.execute(
            f"INSERT INTO users2 (id, email, age) VALUES ({i}, 'u{i % 5}@x', {i})")
    rs = session.execute("SELECT id FROM users2 WHERE email = 'u2@x'")
    assert sorted(r[0] for r in rs.rows) == [2, 7, 12, 17]
    # stale entries filtered after overwrite
    session.execute("UPDATE users2 SET email = 'moved@x' WHERE id = 2")
    rs = session.execute("SELECT id FROM users2 WHERE email = 'u2@x'")
    assert sorted(r[0] for r in rs.rows) == [7, 12, 17]
    rs = session.execute("SELECT id FROM users2 WHERE email = 'moved@x'")
    assert [r[0] for r in rs.rows] == [2]


def test_vector_ann(session):
    session.execute("CREATE TABLE docs (id int PRIMARY KEY, "
                    "embedding vector<float, 4>)")
    session.execute("CREATE CUSTOM INDEX ON docs (embedding) "
                    "USING 'StorageAttachedIndex'")
    import math
    for i in range(50):
        a = i / 50.0 * math.pi
        session.execute("INSERT INTO docs (id, embedding) VALUES (?, ?)",
                        (i, [math.cos(a), math.sin(a), 0.0, 0.0]))
    # query near angle of i=10
    a = 10 / 50.0 * math.pi
    rs = session.execute(
        "SELECT id FROM docs ORDER BY embedding ANN OF ? LIMIT 3",
        ([math.cos(a), math.sin(a), 0.0, 0.0],))
    ids = [r[0] for r in rs.rows]
    assert ids[0] == 10 and set(ids) <= {8, 9, 10, 11, 12}


def test_ucs_strategy(tmp_path):
    from cassandra_tpu.compaction import CompactionManager, get_strategy
    from cassandra_tpu.schema import Schema
    eng = StorageEngine(str(tmp_path / "du"), Schema(), commitlog_sync="batch")
    s = Session(eng)
    s.execute("CREATE KEYSPACE u WITH replication = "
              "{'class': 'SimpleStrategy', 'replication_factor': 1}")
    s.execute("USE u")
    s.execute("CREATE TABLE t (k int PRIMARY KEY, v text) WITH compaction = "
              "{'class': 'UnifiedCompactionStrategy', "
              "'scaling_parameters': 'T4', 'base_shard_count': 2}")
    cfs = eng.store("u", "t")
    for gen in range(4):
        for i in range(50):
            s.execute(f"INSERT INTO t (k, v) VALUES ({i}, 'g{gen}')")
        cfs.flush()
    strat = get_strategy(cfs)
    task = strat.next_background_task()
    assert task is not None and len(task.inputs) == 4
    task.execute()
    assert len(s.execute("SELECT * FROM t").rows) == 50
    assert all(r[0] == "g3" for r in s.execute("SELECT v FROM t").rows)
    eng.close()


def test_writetime_and_ttl_selectors(session):
    session.execute("CREATE TABLE wt (k int PRIMARY KEY, v text, w text)")
    session.execute("INSERT INTO wt (k, v) VALUES (1, 'a') "
                    "USING TIMESTAMP 123456789")
    session.execute("UPDATE wt USING TTL 1000 SET w = 'b' WHERE k = 1")
    rs = session.execute("SELECT writetime(v), ttl(v), ttl(w) FROM wt "
                         "WHERE k = 1")
    wt_v, ttl_v, ttl_w = rs.rows[0]
    assert wt_v == 123456789
    assert ttl_v is None            # no TTL on v
    assert 990 <= ttl_w <= 1000     # remaining TTL on w


def test_writetime_null_for_deleted_and_static(session):
    session.execute("CREATE TABLE wt2 (k int, c int, s text static, v text, "
                    "w text, PRIMARY KEY (k, c))")
    session.execute("INSERT INTO wt2 (k, c, v, w) VALUES (1, 1, 'a', 'b') "
                    "USING TIMESTAMP 777")
    session.execute("INSERT INTO wt2 (k, s) VALUES (1, 'st') "
                    "USING TIMESTAMP 888")
    session.execute("DELETE v FROM wt2 WHERE k = 1 AND c = 1")
    rs = session.execute("SELECT writetime(v), writetime(w), writetime(s) "
                         "FROM wt2 WHERE k = 1")
    wt_v, wt_w, wt_s = rs.rows[0]
    assert wt_v is None           # deleted column: null, not tombstone ts
    assert wt_w == 777
    assert wt_s == 888            # static meta joined


def test_group_by(session):
    session.execute("CREATE TABLE g (k int, c int, v int, "
                    "PRIMARY KEY (k, c))")
    for k in (1, 2):
        for c in range(4):
            session.execute(
                f"INSERT INTO g (k, c, v) VALUES ({k}, {c}, {k * 10 + c})")
    rs = session.execute("SELECT k, count(*), sum(v) FROM g GROUP BY k")
    got = {r[0]: (r[1], r[2]) for r in rs.rows}
    assert got == {1: (4, 10 + 11 + 12 + 13), 2: (4, 20 + 21 + 22 + 23)}
    rs = session.execute("SELECT k, max(v) FROM g WHERE k = 1 GROUP BY k")
    assert rs.rows == [(1, 13)]
    with pytest.raises(Exception):
        session.execute("SELECT v, count(*) FROM g GROUP BY k")  # ungrouped v
    with pytest.raises(Exception):
        session.execute("SELECT count(*) FROM g GROUP BY v")     # non-pk
    rs = session.execute("SELECT * FROM g GROUP BY k")
    assert len(rs.rows) == 2                                     # first/group


def test_limit_applies_after_aggregation(session):
    """LIMIT bounds result groups, not the rows feeding the aggregate
    (cql3 SelectStatement: userLimit applies to the grouped result)."""
    session.execute("CREATE TABLE la (k int, c int, v int, "
                    "PRIMARY KEY (k, c))")
    for k in (1, 2, 3):
        for c in range(5):
            session.execute(
                f"INSERT INTO la (k, c, v) VALUES ({k}, {c}, 1)")
    assert session.execute(
        "SELECT count(*) FROM la LIMIT 1").rows == [(15,)]
    assert session.execute(
        "SELECT sum(v) FROM la LIMIT 3").rows == [(15,)]
    rs = session.execute(
        "SELECT k, count(*) FROM la GROUP BY k LIMIT 2")
    assert len(rs.rows) == 2 and all(n == 5 for _, n in rs.rows)
    # non-aggregate LIMIT still truncates plain rows
    assert len(session.execute("SELECT * FROM la LIMIT 4").rows) == 4


def test_distinct_limit_after_dedup(session):
    session.execute("CREATE TABLE dl (k int, c int, v int, "
                    "PRIMARY KEY (k, c))")
    for k in (1, 2, 3):
        for c in range(5):
            session.execute(
                f"INSERT INTO dl (k, c, v) VALUES ({k}, {c}, 1)")
    rs = session.execute("SELECT DISTINCT k FROM dl LIMIT 2")
    assert len(rs.rows) == 2 and len({r[0] for r in rs.rows}) == 2


def test_select_and_insert_json(session):
    session.execute("CREATE TABLE js (k int PRIMARY KEY, name text, "
                    "nums list<int>, tags set<text>)")
    session.execute('INSERT INTO js JSON '
                    '\'{"k": 1, "name": "ann", "nums": [3, 1], '
                    '"tags": ["x", "y"]}\'')
    import json
    rs = session.execute("SELECT JSON k, name, nums FROM js WHERE k = 1")
    assert rs.column_names == ["[json]"]
    doc = json.loads(rs.rows[0][0])
    assert doc == {"k": 1, "name": "ann", "nums": [3, 1]}
    rs = session.execute("SELECT tags FROM js WHERE k = 1")
    assert rs.rows == [({"x", "y"},)]


def test_token_allocator_balances():
    from cassandra_tpu.cluster.ring import (Endpoint, Ring,
                                            allocate_tokens, even_tokens)
    ring = Ring()
    toks = even_tokens(2, vnodes=4)
    ring.add_node(Endpoint("n1"), toks[0])
    ring.add_node(Endpoint("n2"), toks[1])
    new = allocate_tokens(ring, 4)
    assert len(set(new)) == 4
    all_t = sorted([t for ts in toks for t in ts] + new)
    gaps = [(b - a) for a, b in zip(all_t, all_t[1:])]
    # bisection keeps the spread tight: max gap <= 2.5x min positive gap
    assert max(gaps) <= 2.5 * max(min(gaps), 1)


def test_column_named_json_still_selects(session):
    session.execute("CREATE TABLE j2 (k int PRIMARY KEY, json text)")
    session.execute("INSERT INTO j2 (k, json) VALUES (1, 'doc')")
    assert session.execute("SELECT json FROM j2").rows == [("doc",)]
    assert session.execute("SELECT json, k FROM j2").rows == [("doc", 1)]


def test_insert_json_default_null_and_blob(session):
    session.execute("CREATE TABLE j3 (k int PRIMARY KEY, v text, b blob)")
    session.execute("INSERT INTO j3 (k, v, b) VALUES (1, 'old', 0xaa)")
    session.execute('INSERT INTO j3 JSON \'{"k": 1, "b": "0xff"}\'')
    rs = session.execute("SELECT v, b FROM j3 WHERE k = 1")
    assert rs.rows == [(None, b"\xff")], rs.rows   # omitted v -> null


def test_insert_json_typed_map_keys(session):
    """JSON object keys arrive as strings; they convert by the map's
    KEY TYPE — a boolean key "false" must store as false, not as a
    truthy non-empty string."""
    session.execute("CREATE TABLE jmk (k int PRIMARY KEY, "
                    "bm map<boolean,int>, im map<int,text>)")
    session.execute('INSERT INTO jmk JSON \'{"k": 1, '
                    '"bm": {"false": 10, "true": 20}, '
                    '"im": {"7": "seven"}}\'')
    rs = session.execute("SELECT bm, im FROM jmk WHERE k = 1")
    assert rs.rows == [({False: 10, True: 20}, {7: "seven"})], rs.rows


def test_counter_batch_rules(session):
    """Counters are barred from LOGGED/UNLOGGED batches (batchlog
    replay of a delta double-counts); BEGIN COUNTER BATCH applies
    counter updates and accepts nothing else."""
    session.execute("CREATE TABLE cb (k int PRIMARY KEY, hits counter)")
    session.execute("CREATE TABLE plain (k int PRIMARY KEY, v text)")
    with pytest.raises(InvalidRequest):
        session.execute("BEGIN BATCH "
                        "UPDATE cb SET hits = hits + 1 WHERE k = 1; "
                        "UPDATE cb SET hits = hits + 1 WHERE k = 2; "
                        "APPLY BATCH")
    with pytest.raises(InvalidRequest):
        session.execute("BEGIN COUNTER BATCH "
                        "INSERT INTO plain (k, v) VALUES (1, 'x'); "
                        "APPLY BATCH")
    session.execute("BEGIN COUNTER BATCH "
                    "UPDATE cb SET hits = hits + 4 WHERE k = 1; "
                    "UPDATE cb SET hits = hits - 1 WHERE k = 1; "
                    "APPLY BATCH")
    assert session.execute("SELECT hits FROM cb WHERE k = 1").rows \
        == [(3,)]


def test_row_cache(tmp_path):
    """WITH caching = {'rows_per_partition': 'ALL'}: repeat reads hit
    the cached merged partition; any write to the key invalidates;
    TTL'd partitions are never cached (liveness is clock-dependent)."""
    eng = StorageEngine(str(tmp_path / "rc"), Schema(),
                        commitlog_sync="batch")
    s = Session(eng)
    s.execute("CREATE KEYSPACE ks WITH replication = "
              "{'class': 'SimpleStrategy', 'replication_factor': 1}")
    s.execute("USE ks")
    s.execute("CREATE TABLE kv (k int, c int, v text, "
              "PRIMARY KEY (k, c)) WITH caching = "
              "{'keys': 'ALL', 'rows_per_partition': 'ALL'}")
    cfs = eng.store("ks", "kv")
    assert cfs.row_cache is not None
    for c in range(5):
        s.execute(f"INSERT INTO kv (k, c, v) VALUES (1, {c}, 'x{c}')")
    cfs.flush()
    assert len(s.execute("SELECT c FROM kv WHERE k = 1").rows) == 5
    h0 = cfs.row_cache.hits
    assert len(s.execute("SELECT c FROM kv WHERE k = 1").rows) == 5
    assert cfs.row_cache.hits > h0                     # served cached
    # write invalidates, next read sees the new row
    s.execute("INSERT INTO kv (k, c, v) VALUES (1, 9, 'new')")
    assert len(s.execute("SELECT c FROM kv WHERE k = 1").rows) == 6
    # TTL rows: never cached
    s.execute("INSERT INTO kv (k, c, v) VALUES (2, 0, 't') USING TTL 60")
    s.execute("SELECT c FROM kv WHERE k = 2")
    t = eng.schema.get_table("ks", "kv")
    pk2 = t.columns["k"].cql_type.serialize(2)
    assert cfs.row_cache.get(pk2) is None
    # TRUNCATE clears
    s.execute("TRUNCATE kv")
    assert len(cfs.row_cache) == 0
    # default tables: no row cache
    s.execute("CREATE TABLE plain (k int PRIMARY KEY)")
    assert eng.store("ks", "plain").row_cache is None
    # caching option survives restart
    eng.close()
    eng2 = StorageEngine(str(tmp_path / "rc"), Schema(),
                         commitlog_sync="batch")
    assert eng2.store("ks", "kv").row_cache is not None
    eng2.close()


def test_alter_table_caching(session):
    session.execute("CREATE TABLE ac (k int PRIMARY KEY, v text)")
    cfs = session.processor.executor.backend.store("ks", "ac")
    assert cfs.row_cache is None
    session.execute("ALTER TABLE ac WITH caching = "
                    "{'keys': 'ALL', 'rows_per_partition': 'ALL'}")
    assert cfs.row_cache is not None
    session.execute("INSERT INTO ac (k, v) VALUES (1, 'x')")
    session.execute("SELECT * FROM ac WHERE k = 1")
    session.execute("SELECT * FROM ac WHERE k = 1")
    assert cfs.row_cache.hits >= 1
    session.execute("ALTER TABLE ac WITH caching = "
                    "{'rows_per_partition': 'NONE'}")
    assert cfs.row_cache is None


def test_static_only_partition_produces_row(session):
    """A partition whose only live content is its static row yields ONE
    result row with null clusterings/regulars — point query, range
    scan, and count(*) agree (reference SelectStatement static
    semantics); clustering restrictions exclude it."""
    s = session
    s.execute("CREATE TABLE sonly (k int, c int, v text, "
              "st text static, PRIMARY KEY (k, c))")
    s.execute("UPDATE sonly SET st = 'S1' WHERE k = 1")
    s.execute("UPDATE sonly SET st = 'S2' WHERE k = 2")
    s.execute("INSERT INTO sonly (k, c, v) VALUES (2, 5, 'x')")
    assert s.execute("SELECT k, c, v, st FROM sonly WHERE k = 1").rows \
        == [(1, None, None, "S1")]
    assert sorted(s.execute("SELECT k, c, st FROM sonly").rows) == \
        [(1, None, "S1"), (2, 5, "S2")]
    assert s.execute("SELECT count(*) FROM sonly").rows == [(2,)]
    assert s.execute("SELECT k FROM sonly WHERE k = 1 AND c > 0").rows \
        == []
    # deleting the static content removes the phantom row
    s.execute("DELETE st FROM sonly WHERE k = 1")
    assert s.execute("SELECT k FROM sonly WHERE k = 1").rows == []


def test_static_only_row_with_order_by_and_paging(session):
    """Regression pair: ORDER BY over a result containing a phantom
    static-only row must not crash (nulls group last ascending), and a
    paged scan honors LIMIT across pages with phantom rows present."""
    s = session
    s.execute("CREATE TABLE sol (k int, c int, v text, "
              "st text static, PRIMARY KEY (k, c))")
    s.execute("UPDATE sol SET st = 'S' WHERE k = 1")
    s.execute("INSERT INTO sol (k, c, v) VALUES (1, 9, 'a')")
    s.execute("INSERT INTO sol (k, c, v) VALUES (1, 3, 'b')")
    rows = s.execute("SELECT c FROM sol WHERE k = 1 ORDER BY c ASC").rows
    assert rows == [(3,), (9,)]
    # phantom-only partition under ORDER BY: no crash, null groups last
    s.execute("DELETE FROM sol WHERE k = 1 AND c = 9")
    s.execute("DELETE FROM sol WHERE k = 1 AND c = 3")
    rows = s.execute("SELECT c FROM sol WHERE k = 1 ORDER BY c ASC").rows
    assert rows == [(None,)]
    # paged LIMIT with static-only partitions interleaved
    for k in range(2, 8):
        s.execute(f"UPDATE sol SET st = 'S{k}' WHERE k = {k}")
    for k in (2, 4, 6):
        s.execute(f"INSERT INTO sol (k, c, v) VALUES ({k}, 1, 'r')")
    total = []
    state = None
    while True:
        rs = s.execute("SELECT k FROM sol LIMIT 5", fetch_size=3,
                       paging_state=state)
        total.extend(rs.rows)
        state = rs.paging_state
        if not state:
            break
    assert len(total) == 5, total
