"""COPY TO/FROM migration path + offline sstable tools
(pylib/cqlshlib/copyutil.py, tools/SSTableExport, SSTableMetadataViewer,
StandaloneVerifier roles)."""
import json

import pytest

from cassandra_tpu.cql import Session
from cassandra_tpu.schema import Schema
from cassandra_tpu.storage.engine import StorageEngine
from cassandra_tpu.tools import copyutil, sstabletools


@pytest.fixture
def engine(tmp_path):
    eng = StorageEngine(str(tmp_path / "data"), Schema(),
                        commitlog_sync="batch")
    yield eng
    eng.close()


@pytest.fixture
def session(engine):
    s = Session(engine)
    s.execute("CREATE KEYSPACE ks WITH replication = "
              "{'class': 'SimpleStrategy', 'replication_factor': 1}")
    s.execute("USE ks")
    return s


def test_copy_roundtrip(session, engine, tmp_path):
    session.execute("CREATE TABLE src (id int, seq int, name text, "
                    "score double, ok boolean, data blob, "
                    "PRIMARY KEY (id, seq))")
    for i in range(25):
        session.execute(
            "INSERT INTO src (id, seq, name, score, ok, data) "
            "VALUES (?, ?, ?, ?, ?, ?)",
            (i % 5, i, f"n{i}", i * 1.5, i % 2 == 0, bytes([i]),))
    csv_path = str(tmp_path / "out.csv")
    n = copyutil.copy_to(session, "src", [], csv_path, header=True,
                         fetch_size=7)
    assert n == 25
    session.execute("CREATE TABLE dst (id int, seq int, name text, "
                    "score double, ok boolean, data blob, "
                    "PRIMARY KEY (id, seq))")
    n = copyutil.copy_from(session, engine.schema, "ks", "dst", [],
                           csv_path, header=True)
    assert n == 25
    a = sorted(session.execute(
        "SELECT id, seq, name, score, ok, data FROM src").rows)
    b = sorted(session.execute(
        "SELECT id, seq, name, score, ok, data FROM dst").rows)
    assert a == b


def test_copy_parse():
    spec = copyutil.parse_copy(
        "COPY ks.t (a, b) TO '/tmp/x.csv' WITH HEADER = false;")
    assert spec == {"table": "ks.t", "columns": ["a", "b"],
                    "direction": "to", "path": "/tmp/x.csv",
                    "header": False}
    assert copyutil.parse_copy("COPY t FROM 'f.csv'")["direction"] == "from"
    assert copyutil.parse_copy("SELECT * FROM t") is None


def test_sstabletools_dump_metadata_verify(session, engine, tmp_path):
    session.execute("CREATE TABLE t (k int PRIMARY KEY, v text)")
    for i in range(12):
        session.execute(f"INSERT INTO t (k, v) VALUES ({i}, 'v{i}')")
    engine.store("ks", "t").flush()
    data_dir = engine.data_dir

    rows = sstabletools.dump(data_dir, "ks", "t")
    assert len(rows) == 1
    got = {r["k"]: r["v"] for r in rows[0]["rows"]}
    assert got == {i: f"v{i}" for i in range(12)}

    meta = sstabletools.metadata(data_dir, "ks", "t")
    assert meta[0]["partitions"] == 12
    assert meta[0]["repaired_at"] == 0

    ver = sstabletools.verify(data_dir, "ks", "t")
    assert all(v["status"] == "ok" for v in ver)

    # corrupt one byte of Data.db: verify must notice
    from cassandra_tpu.storage.sstable.format import Component
    sst = engine.store("ks", "t").live_sstables()[0]
    p = sst.desc.path(Component.DATA)
    raw = bytearray(open(p, "rb").read())
    raw[len(raw) // 2] ^= 0xFF
    open(p, "wb").write(bytes(raw))
    ver = sstabletools.verify(data_dir, "ks", "t")
    assert any(v["status"] != "ok" for v in ver)


def test_copy_roundtrip_collections(session, engine, tmp_path):
    session.execute("CREATE TABLE cc (id int PRIMARY KEY, "
                    "tags set<text>, nums list<int>, m map<text, int>)")
    session.execute("INSERT INTO cc (id, tags, nums, m) VALUES "
                    "(1, {'a', 'b''q'}, [3, 1], {'x': 9})")
    session.execute("INSERT INTO cc (id, nums) VALUES (2, [7])")
    p = str(tmp_path / "cc.csv")
    assert copyutil.copy_to(session, "cc", [], p) == 2
    session.execute("CREATE TABLE cc2 (id int PRIMARY KEY, "
                    "tags set<text>, nums list<int>, m map<text, int>)")
    assert copyutil.copy_from(session, engine.schema, "ks", "cc2", [],
                              p) == 2
    a = sorted(session.execute("SELECT id, tags, nums, m FROM cc").rows)
    b = sorted(session.execute("SELECT id, tags, nums, m FROM cc2").rows)
    assert a == b


def test_audit_fql_log(tmp_path):
    import json as _json
    from cassandra_tpu.cql import Session as _S
    eng = StorageEngine(str(tmp_path / "adata"), Schema(),
                        commitlog_sync="batch",
                        audit_log_path=str(tmp_path / "audit.jsonl"))
    try:
        s = _S(eng)
        s.execute("CREATE KEYSPACE ks WITH replication = "
                  "{'class': 'SimpleStrategy', 'replication_factor': 1}")
        s.execute("USE ks")
        s.execute("CREATE TABLE t (k int PRIMARY KEY)")
        s.execute("INSERT INTO t (k) VALUES (1)")
        s.execute("SELECT k FROM t")
        recs = [_json.loads(l) for l in
                open(tmp_path / "audit.jsonl")]
        cats = [r["category"] for r in recs]
        assert "DDL" in cats and "DML" in cats and "QUERY" in cats
        assert any("INSERT INTO t" in r["query"] for r in recs)
    finally:
        eng.close()


def test_cdc_stream(tmp_path):
    from cassandra_tpu.cql import Session as _S
    from cassandra_tpu.storage.cdc import CDCFullException
    eng = StorageEngine(str(tmp_path / "cdata"), Schema(),
                        commitlog_sync="batch")
    try:
        s = _S(eng)
        s.execute("CREATE KEYSPACE ks WITH replication = "
                  "{'class': 'SimpleStrategy', 'replication_factor': 1}")
        s.execute("USE ks")
        s.execute("CREATE TABLE ev (k int PRIMARY KEY, v text) "
                  "WITH cdc = true")
        s.execute("CREATE TABLE quiet (k int PRIMARY KEY)")
        t = eng.schema.get_table("ks", "ev")
        for i in range(5):
            s.execute(f"INSERT INTO ev (k, v) VALUES ({i}, 'v{i}')")
        s.execute("INSERT INTO quiet (k) VALUES (1)")   # not captured
        records = list(eng.cdc.read(t.id))
        assert len(records) == 5
        # the stream replays to real mutations
        _, m = records[0]
        assert m.table_id == t.id and len(m.ops) > 0
        qt = eng.schema.get_table("ks", "quiet")
        assert list(eng.cdc.read(qt.id)) == []
        # consumer checkpoint discards consumed prefix
        off3 = records[2][0]
        eng.cdc.discard(t.id, off3)
        assert len(list(eng.cdc.read(t.id))) == 2
        # capacity: a full stream FAILS cdc writes
        eng.cdc.space_cap = eng.cdc.size(t.id) + 1
        import pytest as _pt
        with _pt.raises(Exception, match="capacity"):
            s.execute("INSERT INTO ev (k, v) VALUES (99, 'x')")
    finally:
        eng.close()


def test_nodetool_scrub_salvages(tmp_path):
    from cassandra_tpu.cql import Session as _S
    from cassandra_tpu.tools import nodetool
    from cassandra_tpu.storage.chunk_cache import GLOBAL as _cache
    from cassandra_tpu.storage.sstable.format import Component
    eng = StorageEngine(str(tmp_path / "sdata"), Schema(),
                        commitlog_sync="batch")
    try:
        s = _S(eng)
        s.execute("CREATE KEYSPACE ks WITH replication = "
                  "{'class': 'SimpleStrategy', 'replication_factor': 1}")
        s.execute("USE ks")
        s.execute("CREATE TABLE t (k int PRIMARY KEY, v text)")
        cfs = eng.store("ks", "t")
        # small segments so one sstable has several (the default segment
        # holds 64K cells)
        import numpy as np
        from cassandra_tpu.storage import cellbatch as cb
        from cassandra_tpu.storage.sstable import Descriptor, SSTableWriter
        from cassandra_tpu.tools import bulk
        t = eng.schema.get_table("ks", "t")
        rng = np.random.default_rng(3)
        batch = bulk.build_int_batch(
            t, np.arange(2000), np.zeros(2000, dtype=np.int64),
            rng.integers(97, 122, (2000, 8), dtype=np.uint8),
            np.full(2000, 100, dtype=np.int64))
        w = SSTableWriter(Descriptor(cfs.directory, cfs.next_generation()),
                          t, segment_cells=512)
        w.append(cb.merge_sorted([batch]))
        w.finish()
        cfs.reload_sstables()
        sst = cfs.live_sstables()[0]
        assert sst.n_segments >= 2
        # corrupt the FIRST segment's bytes on disk
        p = sst.desc.path(Component.DATA)
        raw = bytearray(open(p, "rb").read())
        raw[10] ^= 0xFF
        open(p, "wb").write(bytes(raw))
        _cache._lru.clear(); _cache._sizes.clear(); _cache._bytes = 0
        rep = nodetool.scrub(eng, "ks", "t")
        assert rep[0]["segments_dropped"] == 1
        assert rep[0]["segments_kept"] >= 1
        # the table reads cleanly now (minus the lost segment's cells)
        total = sum(r.n_cells for r in cfs.live_sstables())
        assert 0 < total < 4000
    finally:
        eng.close()
