"""CMS — Paxos-backed commit of cluster-metadata epochs (TCM proper).

Reference counterpart: tcm/PaxosBackedProcessor.java:57 + tcm/Commit.java:
every metadata change (DDL and topology transformations alike) is decided
by single-decree Paxos over a small CMS replica group before any node
applies it. Properties this buys over the round-3 designated-coordinator
scheme (cluster/schema_sync.py history):

  - LINEARIZABLE epochs: slot N is decided once, by a quorum of the CMS
    replica set; two nodes can never durably hold different entries at
    the same epoch, so the adopt-winner/displace repair path is dead code
    for CMS-committed logs.
  - Minority partitions CANNOT commit: a coordinator that cannot reach a
    majority of the CMS set gets MetadataUnavailable, never a local
    fork (tests/test_cms_partition.py).
  - A losing proposer LEARNS the slot winner (from promise fast-path or
    the adopted in-flight value) and retries its own entry at the next
    slot — client-acked DDL is never silently displaced.

The replica-side promise/accept/commit state reuses the LWT machinery
(cluster/paxos.py PaxosState + crash-safe PaxosLog) with the epoch slot
as the partition key, in its own durable log directory (cms_paxos/) —
the system.paxos-for-TCM role of tcm/log/.

CMS membership: the min(3) lowest-named FULLY-JOINED endpoints of the
log-materialized ring (SchemaSync.cms_members) — deterministic at every
node that has applied the same log prefix, and captured ATOMICALLY with
the slot number for each proposal (SchemaSync.snapshot_for_commit), so
two proposers of the same slot always use the same member set and their
quorums intersect. Pending joiners are excluded until their finish_join
entry commits: membership moves only at a committed log entry, and the
OLD set decides the slot that admits the newcomer — mirroring how the
reference reconfigures the CMS explicitly through the log it guards
(tcm/membership/, tcm/ClusterMetadataService.java). Commit-then-apply:
nothing executes locally before the Paxos decision; the proposer applies
its own entry through the same COMMIT/learn path as every replica.
"""
from __future__ import annotations

import json
import os
import threading
import time
import uuid

from .messaging import Message, Verb
from .paxos import Ballot, PaxosLog, PaxosState, ZERO

# pseudo-table id namespacing CMS slots inside the shared PaxosLog frame
# format (real LWT state never collides: this uuid belongs to no table)
CMS_TABLE_ID = uuid.uuid5(uuid.NAMESPACE_DNS, "ctpu.cms.metadata")
CMS_SIZE = 3


class MetadataUnavailable(Exception):
    """A metadata commit could not reach a quorum of the CMS replica
    set (minority partition / too many CMS members down)."""


def _slot_key(slot: int) -> bytes:
    return slot.to_bytes(8, "big")


class CMSService:
    """One node's CMS role: replica handlers (promise/accept/commit on
    epoch slots) + the coordinator-side commit loop."""

    PREPARE = "CMS_PREPARE"
    PROPOSE = "CMS_PROPOSE"
    COMMIT = "CMS_COMMIT"

    ROUND_TIMEOUT = 3.0
    MAX_BALLOT_ATTEMPTS = 10
    MAX_SLOT_ATTEMPTS = 64

    def __init__(self, node, sync, directory: str):
        self.node = node
        self.sync = sync    # SchemaSync: owns the applied epoch log
        self._states: dict[int, PaxosState] = {}
        self._lock = threading.Lock()
        self.log = PaxosLog(os.path.join(directory, "cms_paxos"))
        self._reload()
        ms = node.messaging
        ms.register_handler(self.PREPARE, self._handle_prepare)
        ms.register_handler(self.PROPOSE, self._handle_propose)
        ms.register_handler(self.COMMIT, self._handle_commit)

    # ----------------------------------------------------------- members --

    def members(self) -> list:
        """The CMS replica set as-of THIS node's applied log prefix —
        log-DERIVED, not live-ring-derived (SchemaSync.cms_members):
        pending joiners are not eligible until their finish_join entry
        commits, so the set moves only at a committed log entry and
        the OLD set decides the slot that admits a newcomer. Proposals
        capture (slot, members) atomically via
        SchemaSync.snapshot_for_commit so two proposers of one slot
        always share a member set (intersecting quorums)."""
        return self.sync.cms_members()

    def is_member(self) -> bool:
        return self.node.endpoint in self.members()

    # ----------------------------------------------------------- replicas --

    def _reload(self) -> None:
        for tid, pk, kind, ballot, value in self.log.replay():
            slot = int.from_bytes(pk, "big")
            st = self._state(slot)
            if kind == PaxosLog.K_PROMISE:
                st.promised = max(st.promised, ballot)
            elif kind == PaxosLog.K_ACCEPT:
                st.promised = max(st.promised, ballot)
                st.accepted_ballot = ballot
                st.accepted_value = value
            else:
                st.committed = max(st.committed, ballot)
                if st.accepted_ballot is not None \
                        and st.accepted_ballot <= ballot:
                    st.accepted_ballot = None
                    st.accepted_value = None

    def _state(self, slot: int) -> PaxosState:
        with self._lock:
            st = self._states.get(slot)
            if st is None:
                st = self._states[slot] = PaxosState()
            return st

    def _handle_prepare(self, msg):
        slot, ballot_t = msg.payload
        # fast path: the slot is already applied here — return the
        # committed entry so the proposer learns without a round trip
        ent = self.sync.entry_at(slot)
        if ent is not None:
            _e, query, keyspace, extra, coord = ent
            return "CMS_PROMISE", {
                "committed_entry": {"q": query, "k": keyspace,
                                    "x": extra or {}, "c": coord}}
        ballot = Ballot.unpack(ballot_t)
        st = self._state(slot)
        with st.lock:
            if ballot > st.promised:
                st.promised = ballot
                # durable BEFORE responding (quorum intersection)
                self.log.append(CMS_TABLE_ID, _slot_key(slot),
                                PaxosLog.K_PROMISE, ballot, None)
                rsp = {"promised": True,
                       "accepted_ballot": st.accepted_ballot.pack()
                       if st.accepted_ballot else None,
                       "accepted_value": st.accepted_value}
            else:
                rsp = {"promised": False}
        return "CMS_PROMISE", rsp

    def _handle_propose(self, msg):
        slot, ballot_t, value = msg.payload
        ballot = Ballot.unpack(ballot_t)
        st = self._state(slot)
        with st.lock:
            if ballot >= st.promised:
                st.promised = ballot
                st.accepted_ballot = ballot
                st.accepted_value = value
                self.log.append(CMS_TABLE_ID, _slot_key(slot),
                                PaxosLog.K_ACCEPT, ballot, value)
                rsp = {"accepted": True}
            else:
                rsp = {"accepted": False}
        return "CMS_ACCEPTED", rsp

    def _handle_commit(self, msg):
        slot, ballot_t, value = msg.payload
        ballot = Ballot.unpack(ballot_t)
        st = self._state(slot)
        with st.lock:
            if ballot > st.committed:
                st.committed = ballot
                if st.accepted_ballot is not None \
                        and st.accepted_ballot <= ballot:
                    st.accepted_ballot = None
                    st.accepted_value = None
                self.log.append(CMS_TABLE_ID, _slot_key(slot),
                                PaxosLog.K_COMMIT, ballot, None)
        # apply the decided entry if it is next in sequence (a gap is
        # healed by the SCHEMA_PUSH broadcast / pull catch-up)
        self.sync.learn(slot, json.loads(value))
        return "CMS_COMMITTED", {}

    # -------------------------------------------------------- coordinator --

    def _quorum_round(self, verb: str, payload, members, need: int):
        """One round to the CMS set; self-delivery inline. Returns the
        responses collected before timeout (may be < need — caller
        checks)."""
        node = self.node
        results: list = []
        lock = threading.Lock()
        ev = threading.Event()

        def collect(res):
            with lock:
                results.append(res)
                if len(results) >= need:
                    ev.set()

        handler = {self.PREPARE: self._handle_prepare,
                   self.PROPOSE: self._handle_propose,
                   self.COMMIT: self._handle_commit}[verb]
        for ep in members:
            if ep == node.endpoint:
                m = Message(verb, payload, ep, ep)
                collect(handler(m)[1])
            else:
                node.messaging.send_with_callback(
                    verb, payload, ep,
                    on_response=lambda m: collect(m.payload),
                    timeout=self.ROUND_TIMEOUT)
        ev.wait(self.ROUND_TIMEOUT)
        with lock:
            return list(results)

    _last_ballot_ts = 0
    _ballot_lock = threading.Lock()

    def _next_ballot(self) -> Ballot:
        with CMSService._ballot_lock:
            ts = max(time.time_ns(), CMSService._last_ballot_ts + 1)
            CMSService._last_ballot_ts = ts
        return Ballot(ts, self.node.endpoint.name)

    def _paxos_slot(self, slot: int, value: bytes,
                    members: list) -> bytes:
        """Decide slot among `members` (the set the caller captured
        atomically with the slot number — see snapshot_for_commit):
        returns the DECIDED value bytes (ours, or the winner we must
        apply instead). Raises MetadataUnavailable when a quorum
        cannot be reached."""
        need = len(members) // 2 + 1
        last_err = None
        for attempt in range(self.MAX_BALLOT_ATTEMPTS):
            ballot = self._next_ballot()
            promises = self._quorum_round(
                self.PREPARE, (slot, ballot.pack()), members, need)
            committed = [p for p in promises
                         if isinstance(p, dict) and "committed_entry" in p]
            if committed:
                # slot already decided and applied somewhere: learn it
                return json.dumps(committed[0]["committed_entry"],
                                  sort_keys=True).encode()
            granted = [p for p in promises
                       if isinstance(p, dict) and p.get("promised")]
            if len(promises) < need:
                last_err = MetadataUnavailable(
                    f"CMS prepare: {len(promises)}/{need} of "
                    f"{[m.name for m in members]} responded")
                time.sleep(0.02 * (attempt + 1))
                continue
            if len(granted) < need:
                # contention: back off and retry with a higher ballot
                time.sleep(0.02 * (attempt + 1))
                continue
            # adopt the highest in-flight accepted value, if any
            inflight = [(Ballot.unpack(p["accepted_ballot"]),
                         p["accepted_value"]) for p in granted
                        if p.get("accepted_ballot") is not None]
            proposal = value
            if inflight:
                _b, proposal = max(inflight, key=lambda x: x[0])
            accepts = self._quorum_round(
                self.PROPOSE, (slot, ballot.pack(), proposal),
                members, need)
            ok = [a for a in accepts
                  if isinstance(a, dict) and a.get("accepted")]
            if len(ok) < need:
                last_err = MetadataUnavailable(
                    f"CMS propose: {len(ok)}/{need} accepts")
                time.sleep(0.02 * (attempt + 1))
                continue
            # decided: commit is the learn broadcast (applies via
            # sync.learn on every CMS member; non-members learn from
            # the SCHEMA_PUSH the committer sends after)
            self._quorum_round(self.COMMIT,
                               (slot, ballot.pack(), proposal),
                               members, 1)
            return proposal
        raise last_err or MetadataUnavailable(
            f"CMS slot {slot}: ballot contention exhausted")

    def commit_entry(self, query: str, keyspace, extra: dict,
                     revalidate=None) -> int:
        """Commit (query, keyspace, extra) at the next free epoch.
        COMMIT-THEN-APPLY: the caller must NOT have executed the
        statement — the decided entry applies via the COMMIT
        self-delivery (sync.learn), the same path every replica takes.
        Losing a slot to a concurrent commit applies the winner and
        retries at the next slot (with a re-snapshotted member set —
        the lost slot may have changed CMS membership). `revalidate`
        (no-arg callable raising on semantic error) re-checks the
        statement against the just-applied winner before each retry:
        without it, losing CREATE TABLE t to a concurrent CREATE
        TABLE t would commit a permanently-doomed duplicate entry that
        every node (and every future replay) fails to apply. Returns
        the epoch ours landed at."""
        # normalize through JSON so equality with a decided value is
        # type-faithful (tuples become lists etc.)
        value_dict = json.loads(json.dumps(
            {"q": query, "k": keyspace, "x": extra or {},
             "c": self.node.endpoint.name}, sort_keys=True))
        value = json.dumps(value_dict, sort_keys=True).encode()
        for _ in range(self.MAX_SLOT_ATTEMPTS):
            slot, members = self.sync.snapshot_for_commit()
            decided = self._paxos_slot(slot, value, members)
            ddict = json.loads(decided)
            self.sync.learn(slot, ddict)
            self._push_entry(slot, ddict)
            if ddict == value_dict:
                return slot
            # lost the slot: the winner is applied; ours retries next —
            # unless the winner invalidated it (raises to the client)
            if revalidate is not None:
                revalidate()
        raise MetadataUnavailable(
            f"lost {self.MAX_SLOT_ATTEMPTS} consecutive metadata slots")

    def _push_entry(self, slot: int, ddict: dict) -> None:
        """Broadcast the committed entry to every peer — including
        PENDING joiners and replacements (a mid-join node must track
        the log it is about to become part of; reference
        tcm/log/LocalLog replication reaches registered-but-not-joined
        nodes). Non-CMS nodes learn from this push; stragglers pull."""
        ring = self.node.ring
        targets = set(ring.endpoints) | set(ring.pending) \
            | set(ring.replacing)
        for ep in targets:
            if ep != self.node.endpoint:
                self.node.messaging.send_one_way(
                    Verb.SCHEMA_PUSH,
                    (slot, ddict["q"], ddict["k"], ddict["x"]), ep)
